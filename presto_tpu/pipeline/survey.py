"""One-command search pipeline (the survey-script layer, SURVEY §L7).

The reference orchestrates its searches with per-survey Python drivers
(bin/PALFA_presto_search.py, GBT350_drift_search.py, GBNCC_search.py)
that all run the same canonical flow — the tutorial command history
(docs/GBT_Lband_PSR_cmd_history.txt):

  rfifind -> DDplan -> prepsubband -> realfft -> [zapbirds] ->
  accelsearch -> ACCEL_sift -> prepfold (top cands) ->
  single_pulse_search

This module is that flow as one restartable driver.  Every stage
writes the standard durable artifacts (.mask/.dat/.inf/.fft/
ACCEL_*/cands_sifted.txt/.pfd/.singlepulse), and a stage is skipped
when its outputs are VERIFIED complete (the artifact-per-stage
contract IS the checkpoint system, SURVEY §5.4) — verified, not
merely present: every artifact is written atomically (io/atomic.py)
and journaled with size + CRC-32 in the workdir's manifest.json
(pipeline/manifest.py), so a resume after a kill redoes any stage
whose outputs are missing, truncated, checksum-stale, or were never
journaled, instead of silently trusting whatever bytes survived.

Chaos hooks: SurveyConfig.fault_injector (testing/chaos.py
FaultInjector) is called at every stage and chunk boundary; the chaos
test matrix kills the survey at each point and asserts a resumed run
produces byte-identical final artifacts.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


@dataclass
class SurveyConfig:
    # DM plan
    lodm: float = 0.0
    hidm: float = 100.0
    nsub: int = 32
    # rfifind
    rfi_time: float = 2.0
    # accelsearch
    zmax: int = 0
    numharm: int = 8
    sigma: float = 4.0
    flo: float = 1.0                       # min freq searched (Hz)
    zaplist: Optional[str] = None
    # extra accelsearch passes beyond (zmax, numharm, sigma[, flo]),
    # e.g. the PALFA lo/hi pair — each entry is (zmax, numharm,
    # sigma) or (zmax, numharm, sigma, flo); a 3-tuple inherits flo
    accel_passes: Optional[tuple] = None
    # sifting / folding
    min_dm_hits: int = 2
    low_dm_cutoff: float = 2.0
    fold_top: int = 3
    sift_policy: Optional[object] = None   # sifting.SiftPolicy
    fold_sigma: Optional[float] = None     # fold all cands above this
    max_folds: int = 150                   # ... capped here
    # per-pass fold caps aligned with all_passes, e.g. the GBNCC/
    # GBT350 20-lo + 10-hi split (GBNCC_search.py:21-22,
    # GBT350_drift_search.py:21-22); None -> one combined max_folds
    max_folds_per_pass: Optional[tuple] = None
    # single pulse
    sp_threshold: float = 5.0
    sp_maxwidth: float = 0.0
    singlepulse: bool = True
    skip_rfifind: bool = False
    # barycentre the dedispersed series (drops prepsubband's -nobary).
    # Bary runs flow through the same in-memory stage seam: the
    # resampling consumes the seam series on host and re-deposits, so
    # the .dat spill is byte-equal to a staged bary run's.
    bary: bool = False
    # serving hook: an object with .searcher(acfg, T, numbins) (serve/
    # plancache.SearcherProvider).  None -> build searchers inline, the
    # batch-driver behavior.  A resident service shares one provider
    # across jobs so same-shaped trial groups reuse compiled plans.
    plan_provider: Optional[object] = None
    # fault-tolerance hooks: fault_injector is an object with
    # .point(name) (testing/chaos.FaultInjector) called at stage/chunk
    # boundaries; verify_resume=False reverts to the legacy trust-
    # existence checkpoint contract (no manifest journal).
    fault_injector: Optional[object] = None
    verify_resume: bool = True
    # elastic worker-loss recovery for the DM-sharded prepsubband
    # stage: an ElasticConfig (parallel/elastic.py) or True for
    # defaults.  The stage's DM fan-out then runs as leased shards
    # from the workdir's shard ledger (pipeline/shardledger.py) —
    # a cluster member dying mid-method costs a lease TTL instead of
    # stalling the collective, and a single-host run gains shard-level
    # crash-safe resume.
    elastic: Optional[object] = None
    # observability: an obs.ObsConfig or obs.Observability.  None ->
    # the process default (enabled only when PRESTO_TPU_OBS=1), so an
    # unconfigured run pays one branch per telemetry point and writes
    # no telemetry files — byte-identical to an uninstrumented run.
    obs: Optional[object] = None
    # device-aware autotuning (presto_tpu/tune): True/False forces
    # tuning-DB lookups on/off for this survey; None defers to
    # PRESTO_TPU_TUNE=1.  Tuned knobs pick execution geometry (kernel
    # tile, DM-batch bound, bucket edges) and never change output
    # bytes; a tuned run writes <workdir>/tuned.json provenance
    # (rendered by presto-report).
    tune: Optional[bool] = None
    # stage durability tier (pipeline/fusion.py): stages hand their
    # successors device-resident arrays across an in-memory seam
    # whenever the execution path allows it; durable_stages decides
    # whether the would-be intermediate artifacts (.dat/.fft) are
    # ALSO written+journaled at each boundary.  True (the resolved
    # default) keeps the staged checkpoint contract byte-for-byte
    # (write-through, no read-back); False — the presto-serve/bench
    # tier — skips them, spilling only on demand (prepfold) so a
    # killed run simply redoes the fused stages from the last durable
    # artifact.  None resolves to True unless PRESTO_TPU_DURABLE=0.
    durable_stages: Optional[bool] = None
    # cross-stage in-flight window depth (FFT of DM-group i overlaps
    # search of group i-1); None resolves via the tuning DB's
    # pipeline_inflight_depth family, else the built-in default of 2.
    # Depth only changes dispatch overlap, never output bytes.
    inflight_depth: Optional[int] = None
    # learned candidate triage (presto_tpu/triage): None/False keeps
    # the byte-stable heuristic fold selection; True or a dict
    # {"budget"|"budget_frac", "weights", "borderline_frac"} (or a
    # ready triage.TriagePolicy) reorders/truncates the heuristic
    # selection under a learned score before folding.  Policy, never
    # data path: a missing/corrupt weights file degrades to the
    # heuristic selection unchanged.
    triage: Optional[object] = None

    @property
    def all_passes(self):
        """Normalized 4-tuples (zmax, numharm, sigma, flo)."""
        raw = ((self.zmax, self.numharm, self.sigma, self.flo),) + \
            tuple(self.accel_passes or ())
        return tuple(p if len(p) == 4 else tuple(p) + (self.flo,)
                     for p in raw)


@dataclass
class SurveyResult:
    workdir: str
    maskfile: Optional[str] = None
    datfiles: List[str] = field(default_factory=list)
    candfile: str = ""
    folded: List[str] = field(default_factory=list)
    sp_events: int = 0
    sifted: Optional[object] = None      # sifting.Candlist
    quality: Optional[object] = None     # io/quality.DataQualityReport


def _stage(done_glob: str, workdir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(workdir, done_glob)))


def _chaos(cfg: SurveyConfig, point: str, obs=None) -> None:
    """Fire the configured fault injector at a named kill point.  The
    point is flight-recorded FIRST, so a kill here leaves its own name
    as the dump's final record — the post-mortem starts at the truth."""
    if obs is not None and obs.enabled:
        obs.event("chaos-point", point=point)
    fi = getattr(cfg, "fault_injector", None)
    if fi is not None:
        fi.point(point)


def _valid(manifest, path: str) -> bool:
    """Is this artifact trustworthy for resume?  With a manifest:
    exists AND matches its journaled size+checksum.  Without
    (verify_resume=False): the legacy existence check."""
    if manifest is None:
        return os.path.exists(path)
    return manifest.valid(path)


def _record(manifest, paths, stage: str) -> None:
    if manifest is not None:
        manifest.record_many([p for p in paths if os.path.exists(p)],
                             stage)


def _elastic_argv(elastic_cfg) -> List[str]:
    """Map a SurveyConfig.elastic value (True or an ElasticConfig)
    onto prepsubband -elastic CLI flags."""
    argv = ["-elastic"]
    if elastic_cfg is True:
        return argv
    for flag, attr in (("-shard-rows", "shard_rows"),
                       ("-lease-ttl", "lease_ttl"),
                       ("-barrier-timeout", "barrier_timeout"),
                       ("-heartbeat-interval", "heartbeat_interval")):
        val = getattr(elastic_cfg, attr, None)
        if val:
            argv += [flag, str(val)]
    return argv


def _drop_stale(manifest, paths) -> List[str]:
    """Delete + forget artifacts that fail verification; returns the
    surviving (valid) subset."""
    if manifest is None:
        return [p for p in paths if os.path.exists(p)]
    stale = set(manifest.invalidate_stale(paths))
    return [p for p in paths if p not in stale]


def run_survey(rawfiles: Sequence[str], cfg: SurveyConfig,
               workdir: str = ".", timer=None) -> SurveyResult:
    from presto_tpu.obs import resolve_obs
    obs = resolve_obs(getattr(cfg, "obs", None))
    os.makedirs(workdir, exist_ok=True)
    rawfiles = [os.path.abspath(f) for f in rawfiles]
    base = os.path.join(
        workdir, os.path.splitext(os.path.basename(rawfiles[0]))[0])
    res = SurveyResult(workdir=workdir)
    # crash-safe resume setup: sweep a killed run's in-flight temp
    # files, then load the artifact journal this run will verify
    # against and append to
    from presto_tpu.io.atomic import cleanup_stale_tmp
    cleanup_stale_tmp(workdir)
    manifest = None
    if cfg.verify_resume:
        from presto_tpu.pipeline.manifest import SurveyManifest
        manifest = SurveyManifest.load(workdir)
    if timer is None:
        from presto_tpu.utils.timing import StageTimer
        timer = StageTimer(obs=obs)
    root = obs.span("survey", workdir=workdir,
                    raw=os.path.basename(rawfiles[0]))
    from presto_tpu import tune as _tune
    try:
        with _tune.scoped(cfg.tune):
            result = _run_survey_stages(rawfiles, cfg, workdir, base,
                                        res, timer, manifest, obs)
        root.finish()
        return result
    except BaseException as e:
        # post-mortem on ANY death: unhandled exceptions, typed
        # PrestoIOError, and injected SimulatedCrash (a BaseException)
        # all leave the last N seconds of telemetry next to the
        # artifacts they orphaned
        root.finish("error: %s" % type(e).__name__)
        obs.dump_flight(workdir, reason=type(e).__name__)
        raise
    finally:
        timer.mark(None)
        timer.report()
        # tuned-config provenance beside the artifacts it shaped
        # (written even on death — a post-mortem wants to know which
        # configs were live); no-op when tuning is disabled
        with _tune.scoped(cfg.tune):
            _tune.write_provenance(workdir)
        obs.flush(default_dir=workdir)


def _run_survey_stages(rawfiles, cfg, workdir, base, res, timer,
                       manifest=None, obs=None):
    seam, disk_only = _survey_head(rawfiles, cfg, workdir, base, res,
                                   timer, manifest, obs)
    _device_search_stages(seam, disk_only, res.datfiles, cfg,
                          cfg.all_passes, timer, manifest, obs)
    timer.mark("sift")
    _chaos(cfg, "pre-sift", obs)
    return _finish_survey_stages(rawfiles, cfg, workdir, base, res,
                                 timer, manifest, obs, seam=seam)


def _survey_head(rawfiles, cfg, workdir, base, res, timer,
                 manifest=None, obs=None):
    """Stages 1-3 (rfifind -> DDplan -> prepsubband), depositing the
    DM fan-out at the in-memory stage seam.  Returns (seam,
    disk_only): the seam plus the trials that must flow through the
    original disk consumers.  Split out of _run_survey_stages so the
    stacked cross-job executor (run_survey_stacked) can run N heads
    and then ONE merged device-search stage."""

    timer.mark("rfifind")
    _chaos(cfg, "pre-rfifind", obs)
    # ---- 1. rfifind ---------------------------------------------------
    mask = base + "_rfifind.mask"
    if not cfg.skip_rfifind:
        if not _valid(manifest, mask):
            _drop_stale(manifest,
                        glob.glob(base + "_rfifind.*")
                        + [base + "_rfifind_quality.json"])
            from presto_tpu.apps.rfifind import main as rfifind_main
            rfifind_main(["-time", str(cfg.rfi_time), "-o", base]
                         + rawfiles)
            _record(manifest,
                    glob.glob(base + "_rfifind.*")
                    + [base + "_rfifind_quality.json"], "rfifind")
        res.maskfile = mask
        qpath = base + "_rfifind_quality.json"
        if os.path.exists(qpath):
            from presto_tpu.io.quality import DataQualityReport
            try:
                res.quality = DataQualityReport.read(qpath)
            except (OSError, ValueError):
                pass
        if res.quality is not None and obs is not None:
            # ingest health onto the shared registry: quarantine
            # tallies become /metrics counters, not just per-run JSON
            res.quality.publish(obs.metrics)
    _chaos(cfg, "post-rfifind", obs)

    timer.mark("ddplan")
    # ---- 2. DDplan ----------------------------------------------------
    from presto_tpu.apps.common import open_raw
    from presto_tpu.pipeline.ddplan import Observation, plan_dedispersion
    fb = open_raw(rawfiles)
    hdr = fb.header
    fb.close()
    observation = Observation(dt=hdr.tsamp, f_ctr=hdr.lofreq
                              + 0.5 * (hdr.nchans - 1) * abs(hdr.foff),
                              bw=hdr.nchans * abs(hdr.foff),
                              numchan=hdr.nchans)
    plan = plan_dedispersion(observation, cfg.lodm, cfg.hidm,
                             numsub=cfg.nsub)
    print("survey: DDplan -> %d methods, %d total DMs"
          % (len(plan.methods), plan.total_numdms))

    timer.mark("prepsubband")
    _chaos(cfg, "pre-prepsubband", obs)
    # ---- 3. prepsubband per method ------------------------------------
    # The DM fan-out crosses an IN-MEMORY stage seam
    # (pipeline/fusion.py): prepsubband deposits the device-resident
    # series for the FFT/search/single-pulse stages, and
    # cfg.durable_stages decides whether the .dat artifacts are also
    # written at the boundary (write-through) or only spilled on
    # demand.  The DM-sharded mesh path deposits a ShardedSeamBlock
    # (one DM sub-range per device, consumed in place by the sharded
    # FFT/search below) and barycentred runs re-deposit after the
    # host resampling; only elastic and multi-process runs are
    # seam-incompatible and keep the staged/ledger disk contract —
    # there the seam just stays empty and every consumer below falls
    # back to disk.
    from presto_tpu.apps.prepsubband import main as prepsubband_main
    from presto_tpu.pipeline import fusion
    seam = fusion.StageSeam(workdir, durable=_durable(cfg),
                            manifest=manifest, obs=obs,
                            inflight_depth=cfg.inflight_depth)
    dat_glob = os.path.basename(base) + "_DM*.dat"
    # verify survivors of a previous run ONCE, before the loop — this
    # run's own per-method outputs are journaled as each method lands,
    # so they must not be re-judged (and deleted) mid-flight
    _drop_stale(manifest, _stage(dat_glob, workdir))
    for m in plan.methods:
        have = _stage(dat_glob, workdir)
        missing = [dm for dm in m.dms
                   if not any("_DM%.2f.dat" % dm in f for f in have)]
        if not missing:
            continue
        argv = ["-lodm", str(m.lodm), "-dmstep", str(m.ddm),
                "-numdms", str(m.numdms), "-nsub", str(cfg.nsub),
                "-downsamp", str(m.downsamp), "-o", base]
        if not getattr(cfg, "bary", False):
            argv += ["-nobary"]
        if res.maskfile and os.path.exists(res.maskfile):
            argv += ["-mask", res.maskfile]
        if getattr(cfg, "elastic", None):
            # worker-loss-tolerant DM fan-out: run the method through
            # the leased-shard ledger (apps/prepsubband -elastic);
            # the survey's chaos injector threads through the elastic
            # layer's process seam (argv can't carry objects)
            from presto_tpu.parallel import elastic as _elastic
            argv += _elastic_argv(cfg.elastic)
            _elastic.set_process_injector(cfg.fault_injector)
            _elastic.set_process_obs(obs)
            try:
                prepsubband_main(argv + rawfiles)
            finally:
                _elastic.set_process_injector(None)
                _elastic.set_process_obs(None)
            _chaos(cfg, "elastic-method", obs)
        elif os.environ.get("PRESTO_TPU_FUSION", "1") == "0":
            # operational kill switch: keep the pre-fusion staged
            # contract exactly (every stage boundary on disk)
            prepsubband_main(argv + rawfiles)
        else:
            fusion.set_process_seam(seam)
            try:
                prepsubband_main(argv + rawfiles)
            finally:
                fusion.set_process_seam(None)
        done = _stage(dat_glob, workdir)
        _record(manifest, done + [f[:-4] + ".inf" for f in done],
                "prepsubband")
        _chaos(cfg, "prepsubband-method", obs)
    disk_dats = _stage(dat_glob, workdir)
    seam_set = {os.path.abspath(p) for p in seam.dat_paths()}
    res.datfiles = sorted(set(disk_dats)
                          | {os.path.join(workdir, os.path.basename(p))
                             for p in seam.dat_paths()})
    # trials the seam does NOT hold (a previous staged run's verified
    # survivors, or a seam-incompatible execution path): these flow
    # through the original disk consumers below
    disk_only = [f for f in res.datfiles
                 if os.path.abspath(f) not in seam_set]
    n_sharded = sum(len(b.names) for b in seam.blocks
                    if fusion.is_sharded(b))
    print("survey: %d dedispersed time series (%d seam-resident, "
          "%d sharded)" % (len(res.datfiles), len(seam), n_sharded))
    _chaos(cfg, "seam-handoff", obs)
    if n_sharded:
        _chaos(cfg, "shard-seam-handoff", obs)
    _chaos(cfg, "post-prepsubband", obs)
    return seam, disk_only


def _device_search_stages(seam, disk_only, datfiles, cfg, passes,
                          timer, manifest=None, obs=None):
    """Stages 9a + 4/5/6: single-pulse, rFFT, (zapbirds), accelsearch
    over the seam-resident series plus the disk-trial fallbacks.  This
    is the survey's device-bound middle — exactly what the stacked
    serve executor runs ONCE over a merged cross-job seam
    (run_survey_stacked) instead of once per job."""

    # ---- 9a. single-pulse search over the seam-resident series ------
    # runs BEFORE the FFT consumes (and may donate) the series block;
    # artifacts and candidate sets are byte-identical to the staged
    # stage-ordered run — only the wall-clock attribution moves.
    if cfg.singlepulse and len(seam):
        timer.mark("single_pulse")
        _seam_singlepulse(seam, cfg, manifest, obs)

    from dataclasses import replace as _replace
    if cfg.zaplist:
        timer.mark("realfft")
        if len(seam):
            # seam trials: FFT + in-memory zap + every accel pass
            # without touching disk (spectra spilled only on the
            # durable tier, journaled at the post-zap "zapbirds" state)
            timer.mark("realfft+accelsearch (fused)")
            _seam_fft_search(seam, cfg, passes, manifest, obs,
                             zap=True)
            timer.mark("realfft")
        _staged_fft_search_head(disk_only, cfg, manifest, obs)
        # the staged sweep covers disk trials AND any seam trial whose
        # zapped spectrum already sits journaled on disk (re-zapping
        # is excluded by contract, so those search from the artifact)
        fftfiles = sorted({f[:-4] + ".fft" for f in disk_only}
                          | {f[:-4] + ".fft" for f in datfiles
                             if os.path.exists(f[:-4] + ".fft")})
        timer.mark("zapbirds")
        # ---- 5. zapbirds ---------------------------------------------
        # zapping mutates the .fft in place and is NOT idempotent, so
        # the journal's stage tag is the checkpoint: a spectrum whose
        # entry already says "zapbirds" (and still verifies) is done.
        from presto_tpu.apps.zapbirds import main as zap_main
        for f in fftfiles:
            if (manifest is not None and manifest.valid(f)
                    and manifest.stage_of(f) == "zapbirds"):
                continue
            zap_main(["-zap", "-zapfile", cfg.zaplist, f])
            _record(manifest, [f], "zapbirds")
            _chaos(cfg, "zapbirds-file", obs)
        timer.mark("accelsearch")
        # ---- 6. accelsearch: BATCHED over the DM fan-out, once per
        # recipe pass (e.g. PALFA's zmax=0/nh=16 + zmax=50/nh=8) -----
        for (zmax, nh, sg, flo) in passes:
            _batched_accelsearch(
                fftfiles, _replace(cfg, zmax=zmax, numharm=nh,
                                   sigma=sg, flo=flo), manifest, obs)
    else:
        # ---- 4+6 fused fast path: realfft -> accelsearch with the
        # spectra RESIDENT on device (no zapbirds in between).  Seam
        # trials never touch disk at all (the dedisp output block is
        # the FFT input block, donated where the backend supports it);
        # disk trials keep the read-once upload path.  ACCEL artifacts
        # are always written, preserving the checkpoint contract.
        timer.mark("realfft+accelsearch (fused)")
        if len(seam):
            _seam_fft_search(seam, cfg, passes, manifest, obs)
        _fused_fft_search(disk_only, cfg, manifest, obs)
        for (zmax, nh, sg, flo) in passes:
            # resume case for the first pass; full searches for the
            # recipe's additional passes
            _batched_accelsearch(
                [f[:-4] + ".fft" for f in disk_only],
                _replace(cfg, zmax=zmax, numharm=nh, sigma=sg,
                         flo=flo), manifest, obs)


def _length_groups(files, item_bytes):
    """Group files by payload length (dict length -> file list);
    item_bytes converts a file size to its logical length."""
    by_len = {}
    for f in files:
        by_len.setdefault(item_bytes(os.path.getsize(f)), []).append(f)
    return by_len


def _durable(cfg) -> bool:
    """Resolve the stage-durability tier: an explicit
    cfg.durable_stages wins; None defaults to durable (the
    resume-critical contract) unless PRESTO_TPU_DURABLE=0."""
    d = getattr(cfg, "durable_stages", None)
    if d is not None:
        return bool(d)
    return os.environ.get("PRESTO_TPU_DURABLE", "1") != "0"


def _searcher_for(cfg, T, nbins):
    """One accel searcher for a (pass config, duration, length) —
    through the plan provider when a resident service shares one
    (serve/plancache), so same-shaped trial groups reuse compiled
    plans across the staged AND seam paths."""
    from presto_tpu.search.accel import AccelConfig, AccelSearch
    acfg = AccelConfig(zmax=cfg.zmax, numharm=cfg.numharm,
                       sigma=cfg.sigma, flo=cfg.flo)
    if cfg.plan_provider is not None:
        return cfg.plan_provider.searcher(acfg, T, nbins)
    return AccelSearch(acfg, T=T, numbins=nbins)


def _survey_searcher(first_file, nbins, cfg):
    """(searcher, T) for one same-length trial group."""
    from presto_tpu.io.infodata import read_inf
    info = read_inf(first_file[:-4] + ".inf")
    T = info.N * info.dt
    return _searcher_for(cfg, T, nbins), T


def _seam_fft_search(seam, cfg, passes, manifest=None, obs=None,
                     zap=False) -> None:
    """Every accel pass over the seam-resident series: batched rfft
    straight off the dedisp output block (donated to the FFT where
    the backend supports aliasing), search_many on the device
    spectra, ONE download per chunk for candidate refinement (and the
    durable tier's .fft spill).  Dispatch of chunk i+1's FFT is
    admitted to the in-flight window before chunk i's results are
    collected, so the host-side refine/write of one chunk overlaps
    the device work of the next.

    With ``zap`` the downloaded spectrum is zapped in memory
    (apps/zapbirds.zap_pairs_batch) and the ZAPPED pairs are what the
    search consumes — the staged rfft->zapbirds->accelsearch flow
    without the two disk round-trips.  Durable spills journal the
    .fft at its post-zap state (stage "zapbirds"), matching the
    staged journal's non-idempotency contract; a trial whose .fft is
    already journaled zapped is left to the disk consumers
    (re-zapping is not byte-stable).

    Sharded seam blocks stay sharded through the whole chain: the
    batched rFFT keeps each device's spectra resident
    (fused_rfft_batch with the mesh's out_shardings), the search runs
    shard_map'd in place (search_many(mesh=...)), and the single bulk
    download is the per-shard gather that feeds zap/refine/spill."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dataclasses import replace as _replace
    from presto_tpu.apps.accelsearch import refine_and_write
    from presto_tpu.io import datfft
    from presto_tpu.obs import jaxtel
    from presto_tpu.ops import fftpack
    from presto_tpu.pipeline import fusion

    try:
        can_donate = jax.devices()[0].platform != "cpu"
    except Exception:
        can_donate = False

    def collect(ent):
        """Search + refine + write one FFT'd chunk (the sync point)."""
        (block, rows, pairs_dev, todo_passes, n, mesh) = ent
        nbins = n // 2
        T = block.numout * fusion.inf_float(block.dt)
        if mesh is not None:
            # per-shard D2H (candidate collection + durable spill)
            pairs_host = fusion.gather_shards(pairs_dev, obs=obs)
        else:
            pairs_host = np.array(pairs_dev)      # one download
            jaxtel.note_get(obs, pairs_host.nbytes)
        search_dev = pairs_dev
        if zap and cfg.zaplist:
            from presto_tpu.apps.zapbirds import zap_pairs_batch
            pairs_host = zap_pairs_batch(pairs_host, cfg.zaplist, T,
                                         block.numout)
            if mesh is not None:      # re-upload zapped, per shard
                from presto_tpu.parallel.mesh import dm_sharding
                search_dev = jax.device_put(pairs_host,
                                            dm_sharding(mesh, 3))
            else:
                search_dev = jnp.asarray(pairs_host)
            jaxtel.note_put(obs, pairs_host.nbytes)
            _chaos(cfg, "zapbirds-file", obs)
        for pcfg in todo_passes:
            searcher = _searcher_for(pcfg, T, nbins)
            jaxtel.note_dispatch(obs, "accel_search")
            results = searcher.search_many(search_dev, mesh=mesh,
                                           obs=obs)
            arts = []
            for row, pr, raw in zip(rows, pairs_host, results):
                name = block.names[row]
                amps = fftpack.np_pairs_to_complex64(pr)
                refine_and_write(raw, amps, T, searcher, name,
                                 pcfg.zmax, quiet=True)
                acc = name + "_ACCEL_%d" % pcfg.zmax
                arts += [acc, acc + ".cand"]
            _record(manifest, arts, "accel" if zap else "fft+accel")
        if seam.durable:
            ffts = []
            for row, pr in zip(rows, pairs_host):
                f = block.names[row] + ".fft"
                datfft.write_fft(f, fftpack.np_pairs_to_complex64(pr))
                ffts.append(f)
            _record(manifest, ffts, "zapbirds" if zap else "fft+accel")
        jaxtel.sample_live_buffers(obs)
        _chaos(cfg, "fused-chunk", obs)
        if mesh is not None:
            _chaos(cfg, "sharded-fused-chunk", obs)

    ndone = 0
    pending = []          # the cross-stage in-flight window: chunk
    depth = seam.depths["window"]   # i+1's FFT is queued on the
    shard_depth = seam.depths["shard_window"]   # device before chunk
    for numout, blocks in sorted(seam.groups().items()):  # i's host
        n = numout & ~1   # collection starts
        for block in blocks:
            sharded = fusion.is_sharded(block)
            mesh = block.mesh if sharded else None
            ndev = (len(list(mesh.devices.flat)) if sharded else 1)
            # the staged consumers' verify-or-redo contract, per trial
            arts = []
            for name in block.names:
                for (zmax, _nh, _sg, _flo) in passes:
                    acc = name + "_ACCEL_%d" % zmax
                    arts += [acc, acc + ".cand"]
            _drop_stale(manifest, arts)
            rows = []
            for row, name in enumerate(block.names):
                if zap and manifest is not None and \
                        _valid(manifest, name + ".fft") and \
                        manifest.stage_of(name + ".fft") == "zapbirds":
                    continue     # journaled zapped spectrum: disk path
                need = any(
                    not (_valid(manifest, name + "_ACCEL_%d" % zmax)
                         and _valid(manifest,
                                    name + "_ACCEL_%d.cand" % zmax))
                    for (zmax, _nh, _sg, _flo) in passes)
                if need or (seam.durable
                            and not _valid(manifest, name + ".fft")):
                    rows.append(row)
            if not rows:
                continue
            todo_passes = [_replace(cfg, zmax=z, numharm=nh, sigma=sg,
                                    flo=flo)
                           for (z, nh, sg, flo) in passes]
            # memory budget is per DEVICE: a sharded whole-block holds
            # numdms/ndev rows on each chip
            per = max(1, int(2 ** 30 // max(n * 4, 1))) * ndev
            whole = rows == list(range(len(block.names))) \
                and len(rows) <= per
            # a partial sharded block (mixed resume) gathers its rows
            # off the mesh and takes the single-device path below
            chunk_mesh = mesh if (sharded and whole) else None
            for g0 in range(0, len(rows), per):
                chunk_rows = rows[g0:g0 + per]
                span = (obs.span("sharded-fused-chunk" if chunk_mesh
                                 is not None else "fused-chunk",
                                 files=len(chunk_rows), nbins=n)
                        if obs is not None else None)
                if whole and can_donate:
                    # the dedisp output block IS the FFT input block:
                    # donate it (input [nd, n] f32 and output
                    # [nd, n/2, 2] f32 are the same size, so the seam
                    # crossing is allocation-neutral); the host copy
                    # stays for spills.  CPU's XLA cannot alias these
                    # and would only warn.
                    chunk_dev = block.series_dev[:, :n]
                    seam.release(block)
                    pairs_dev = fusion.fused_rfft_batch(
                        chunk_dev, donate=True, obs=obs,
                        mesh=chunk_mesh)
                elif whole:
                    pairs_dev = fusion.fused_rfft_batch(
                        block.series_dev[:, :n], obs=obs,
                        mesh=chunk_mesh)
                else:
                    pairs_dev = fusion.fused_rfft_batch(
                        block.series_dev[np.asarray(chunk_rows), :n],
                        obs=obs)
                pending.append((block, chunk_rows, pairs_dev,
                                todo_passes, n, chunk_mesh))
                window = (shard_depth if chunk_mesh is not None
                          else depth)
                while len(pending) >= max(window, 1):
                    collect(pending.pop(0))
                    ndone += 1
                if span is not None:
                    span.finish()
    while pending:
        collect(pending.pop(0))
        ndone += 1
    if ndone:
        print("survey: fused realfft+accelsearch over %d seam chunks "
              "(device-resident, %d passes%s)"
              % (ndone, len(passes), ", zap" if zap else ""))


def _seam_singlepulse(seam, cfg, manifest=None, obs=None) -> None:
    """Single-pulse search over the seam-resident series: the exact
    app pipeline (apps/single_pulse_search) fed from HBM instead of a
    third .dat disk read + re-upload.  Inputs are bit-equal to the
    staged path's (same padded series, same .inf-roundtripped dt/dm,
    same onoff-derived offregions), so the .singlepulse artifacts are
    byte-identical.

    Sharded blocks search PER SHARD: each mesh device's DM sub-range
    runs search_many_resident on the device that dedispersed it (the
    per-file results are independent of batch composition, so shard
    batches equal the whole-batch candidate sets) — no gather, no
    re-upload.  A partially-resumed sharded block falls back to the
    row-stacking path below."""
    import jax.numpy as jnp
    from presto_tpu.apps.single_pulse_search import (sp_block_plan,
                                                     sp_input_plan)
    from presto_tpu.obs import jaxtel
    from presto_tpu.pipeline import fusion
    from presto_tpu.search.singlepulse import (SinglePulseSearch,
                                               write_singlepulse)

    sp = SinglePulseSearch(threshold=cfg.sp_threshold,
                           maxwidth=cfg.sp_maxwidth)
    planned = []          # (block, row, nuse, offregions)
    sharded_todo = []     # (block, nuse, offregions): whole blocks
    spfiles = [name + ".singlepulse" for b in seam.blocks
               for name in b.names]
    _drop_stale(manifest, spfiles)
    nsh = 0
    for block in seam.blocks:
        rows_todo = [row for row, name in enumerate(block.names)
                     if not _valid(manifest, name + ".singlepulse")]
        if not rows_todo:
            continue
        if fusion.is_sharded(block) and \
                rows_todo == list(range(len(block.names))):
            bplan = sp_block_plan(block.infos, block.numout)
            if bplan is not None:
                sharded_todo.append((block,) + tuple(bplan))
                nsh += len(rows_todo)
                continue
        for row in rows_todo:
            nuse, offregions = sp_input_plan(block.infos[row],
                                             block.numout)
            planned.append((block, row, nuse, offregions))

    nev = 0
    for block, nuse, offregions in sharded_todo:
        bdt = fusion.inf_float(block.dt)
        for sh in block.series_dev.addressable_shards:
            lo = sh.index[0].start or 0
            batch = sh.data[:, :nuse]       # stays on sh's device
            rows = list(range(lo, lo + int(batch.shape[0])))
            span = (obs.span("sp-seam-chunk", files=len(rows),
                             nuse=nuse, sharded=True)
                    if obs is not None else None)
            jaxtel.note_dispatch(obs, "sp_search")
            results = sp.search_many_resident(
                batch, bdt,
                dms=[fusion.inf_float(block.infos[r].dm, 12)
                     for r in rows],
                offregions_list=[offregions] * len(rows), obs=obs)
            written = []
            for r, (cands, _stds, _bad) in zip(rows, results):
                f = block.names[r] + ".singlepulse"
                write_singlepulse(f, cands)
                written.append(f)
                nev += len(cands)
            _record(manifest, written, "singlepulse")
            if span is not None:
                span.finish()
            _chaos(cfg, "sp-seam-chunk", obs)
    if not planned:
        if nsh:
            print("survey: single-pulse search over %d seam-resident "
                  "series (%d events, sharded)" % (nsh, nev))
        return
    groups = {}
    for item in planned:
        key = (item[2], fusion.inf_float(item[0].dt))
        groups.setdefault(key, []).append(item)
    for (nuse, dt), items in sorted(groups.items()):
        per = max(1, int(2 ** 30 // max(nuse * 4, 1)))
        for g0 in range(0, len(items), per):
            chunk = items[g0:g0 + per]
            span = (obs.span("sp-seam-chunk", files=len(chunk),
                             nuse=nuse)
                    if obs is not None else None)
            batch = jnp.stack([b.series_dev[row, :nuse]
                               for (b, row, _n, _o) in chunk])
            jaxtel.note_dispatch(obs, "sp_search")
            results = sp.search_many_resident(
                batch, dt,
                dms=[fusion.inf_float(b.infos[row].dm, 12)
                     for (b, row, _n, _o) in chunk],
                offregions_list=[o for (_b, _r, _n, o) in chunk],
                obs=obs)
            written = []
            for (b, row, _n, _o), (cands, _stds, bad) in zip(chunk,
                                                             results):
                f = b.names[row] + ".singlepulse"
                write_singlepulse(f, cands)
                written.append(f)
                nev += len(cands)
            _record(manifest, written, "singlepulse")
            if span is not None:
                span.finish()
            _chaos(cfg, "sp-seam-chunk", obs)
    print("survey: single-pulse search over %d seam-resident series "
          "(%d events%s)" % (len(planned) + nsh, nev,
                             ", %d sharded" % nsh if nsh else ""))


def _fused_fft_search(datfiles, cfg, manifest=None, obs=None) -> None:
    """Stage 4+6 fused (disk trials): batched rfft, search_many on the
    DEVICE spectra, one download for the .fft artifacts.  Only
    processes trials with NO verified .fft yet — existing valid
    spectra (an interrupted run's checkpoints) are left to
    _batched_accelsearch so their upload isn't paid twice."""
    _drop_stale(manifest, [f[:-4] + ".fft" for f in datfiles])
    todo = [f for f in datfiles
            if not _valid(manifest, f[:-4] + ".fft")]
    if not todo:
        return
    import jax
    import jax.numpy as jnp
    import numpy as np
    from presto_tpu.io import datfft
    from presto_tpu.obs import costmodel, jaxtel
    from presto_tpu.ops import fftpack
    from presto_tpu.apps.accelsearch import refine_and_write

    batched = jax.jit(jax.vmap(fftpack.realfft_packed_pairs))
    for n, files in _length_groups(
            todo, lambda sz: (sz // 4) & ~1).items():
        searcher, T = _survey_searcher(files[0], n // 2, cfg)
        per = max(1, int(2 ** 30 // max(n * 4, 1)))
        for g0 in range(0, len(files), per):
            chunk = files[g0:g0 + per]
            sp = (obs.span("fused-chunk", files=len(chunk), nbins=n)
                  if obs is not None else None)
            arr = np.stack([datfft.read_dat(f)[:n] for f in chunk])
            jaxtel.note_put(obs, arr.nbytes)
            costmodel.probe(obs, "rfft_batch", batched, arr)
            jaxtel.note_dispatch(obs, "rfft_batch")
            pairs_dev = batched(jnp.asarray(arr))    # stays in HBM
            jaxtel.note_dispatch(obs, "accel_search")
            results = searcher.search_many(pairs_dev, obs=obs)
            pairs_host = np.asarray(pairs_dev)       # one download
            jaxtel.note_get(obs, pairs_host.nbytes)
            arts = []
            for f, pr, raw in zip(chunk, pairs_host, results):
                amps = fftpack.np_pairs_to_complex64(pr)
                datfft.write_fft(f[:-4] + ".fft", amps)
                refine_and_write(raw, amps, T, searcher, f[:-4],
                                 cfg.zmax, quiet=True)
                acc = f[:-4] + "_ACCEL_%d" % cfg.zmax
                arts += [f[:-4] + ".fft", acc, acc + ".cand"]
            _record(manifest, arts, "fft+accel")
            jaxtel.sample_live_buffers(obs)
            if sp is not None:
                sp.finish()
            _chaos(cfg, "fused-chunk", obs)
    print("survey: fused realfft+accelsearch over %d trials "
          "(device-resident spectra)" % len(todo))


def _staged_fft_search_head(datfiles, cfg, manifest=None, obs=None):
    """Stage 4 alone (the staged path used when zapbirds intervenes).

    Resume caveat: an .fft the journal marks "zapbirds" is a ZAPPED
    spectrum — still valid, must not be regenerated (that would undo
    the zap and desync the stage tag)."""
    _drop_stale(manifest, [f[:-4] + ".fft" for f in datfiles])
    todo = [f for f in datfiles
            if not _valid(manifest, f[:-4] + ".fft")]
    if todo:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from presto_tpu.io import datfft
        from presto_tpu.obs import costmodel, jaxtel
        from presto_tpu.ops import fftpack
        batched = jax.jit(jax.vmap(fftpack.realfft_packed_pairs))
        for n, files in _length_groups(
                todo, lambda sz: (sz // 4) & ~1).items():
            # memory budget: read/stack/upload at most ~1 GB per group
            per = max(1, int(2 ** 30 // max(n * 4, 1)))
            for g0 in range(0, len(files), per):
                chunk = files[g0:g0 + per]
                sp = (obs.span("fft-chunk", files=len(chunk), nbins=n)
                      if obs is not None else None)
                # no mean subtraction: byte parity with the realfft
                # app (bin 0 is outside the searched range anyway)
                arr = np.stack([datfft.read_dat(f)[:n] for f in chunk])
                jaxtel.note_put(obs, arr.nbytes)
                costmodel.probe(obs, "rfft_batch", batched, arr)
                jaxtel.note_dispatch(obs, "rfft_batch")
                pairs = np.asarray(batched(jnp.asarray(arr)))
                jaxtel.note_get(obs, pairs.nbytes)
                for f, pr in zip(chunk, pairs):
                    datfft.write_fft(f[:-4] + ".fft",
                                     fftpack.np_pairs_to_complex64(pr))
                _record(manifest, [f[:-4] + ".fft" for f in chunk],
                        "realfft")
                if sp is not None:
                    sp.finish()
                _chaos(cfg, "fft-chunk", obs)
        print("survey: realfft over %d series (batched)" % len(todo))


def _batched_accelsearch(fftfiles, cfg, manifest=None, obs=None):
    """Stage 6 alone (staged path): grouped search_many over .fft
    files already on disk."""
    accs = [f[:-4] + "_ACCEL_%d" % cfg.zmax for f in fftfiles]
    # the ACCEL table and its binary .cand companion are one logical
    # artifact: either going stale redoes both
    _drop_stale(manifest, accs + [a + ".cand" for a in accs])
    todo = [f for f, a in zip(fftfiles, accs)
            if not (_valid(manifest, a)
                    and _valid(manifest, a + ".cand"))]
    if todo:
        import numpy as np
        from presto_tpu.io import datfft
        from presto_tpu.obs import jaxtel
        from presto_tpu.ops import fftpack
        from presto_tpu.apps.accelsearch import refine_and_write
        for nbins, files in _length_groups(
                todo, lambda sz: sz // 8).items():
            searcher, T = _survey_searcher(files[0], nbins, cfg)
            # memory budget ~1 GB of host spectra per batched call
            per = max(1, int(2 ** 30 // max(nbins * 8, 1)))
            for g0 in range(0, len(files), per):
                chunk = files[g0:g0 + per]
                sp = (obs.span("accel-chunk", files=len(chunk),
                               nbins=nbins, zmax=cfg.zmax)
                      if obs is not None else None)
                amps_list = [datfft.read_fft(f) for f in chunk]
                batch = np.stack([fftpack.np_complex64_to_pairs(a)
                                  for a in amps_list])
                jaxtel.note_put(obs, batch.nbytes)
                jaxtel.note_dispatch(obs, "accel_search")
                results = searcher.search_many(batch, obs=obs)
                arts = []
                for f, amps, raw in zip(chunk, amps_list, results):
                    refine_and_write(raw, amps, T, searcher, f[:-4],
                                     cfg.zmax, quiet=True)
                    acc = f[:-4] + "_ACCEL_%d" % cfg.zmax
                    arts += [acc, acc + ".cand"]
                _record(manifest, arts, "accel")
                jaxtel.sample_live_buffers(obs)
                if sp is not None:
                    sp.finish()
                _chaos(cfg, "accel-chunk", obs)
        print("survey: accelsearch over %d trials (batched)"
              % len(todo))


def resolve_triage_policy(spec, datdir):
    """cfg.triage -> a sifting policy callable (or None).

    Accepts None/False (off), True (defaults), a dict with any of
    {"budget", "budget_frac", "weights", "borderline_frac"}, or an
    already-built triage.TriagePolicy (returned as-is, datdir filled
    if unset)."""
    if not spec:
        return None
    from presto_tpu.triage import TriagePolicy
    if isinstance(spec, TriagePolicy):
        if spec.datdir is None:
            spec.datdir = datdir
        return spec
    kw = spec if isinstance(spec, dict) else {}
    return TriagePolicy(weights_path=kw.get("weights"),
                        budget=kw.get("budget"),
                        budget_frac=kw.get("budget_frac"),
                        borderline_frac=kw.get("borderline_frac", 0.25),
                        datdir=datdir)


def _finish_survey_stages(rawfiles, cfg, workdir, base, res, timer,
                          manifest=None, obs=None, seam=None):
    # ---- 7. sift ------------------------------------------------------
    from presto_tpu.pipeline.sifting import sift_candidates
    accfiles = []
    for (zmax, _nh, _sg, _flo) in cfg.all_passes:
        accfiles += _stage(os.path.basename(base)
                           + "_DM*_ACCEL_%d" % zmax, workdir)
    accfiles = sorted(set(accfiles))
    res.candfile = os.path.join(workdir, "cands_sifted.txt")
    cl = sift_candidates(accfiles, numdms_min=cfg.min_dm_hits,
                         low_DM_cutoff=cfg.low_dm_cutoff,
                         policy=cfg.sift_policy)
    cl.to_file(res.candfile)
    _record(manifest, [res.candfile], "sift")
    res.sifted = cl
    print("survey: %d sifted candidates -> %s"
          % (len(cl), res.candfile))
    _chaos(cfg, "post-sift", obs)

    timer.mark("prepfold")
    # ---- 8. fold the top candidates -----------------------------------
    # recipe policy: fold everything above to_prepfold_sigma, never
    # more than max_folds (PALFA_presto_search.py:32-33); per-pass
    # caps split the budget by search pass, e.g. 20 lo-accel + 10
    # hi-accel (GBNCC_search.py:479-486).  The selection itself is
    # shared with the discovery-DAG sift node (sifting.py), so a DAG
    # fans out exactly the folds this driver would run.
    from presto_tpu.apps.prepfold import main as prepfold_main
    from presto_tpu.pipeline.sifting import select_fold_candidates
    accounting = {}
    top = select_fold_candidates(
        cl, fold_top=cfg.fold_top, fold_sigma=cfg.fold_sigma,
        max_folds=cfg.max_folds,
        max_folds_per_pass=cfg.max_folds_per_pass,
        pass_zmaxes=[z for (z, _nh, _sg, _flo) in cfg.all_passes],
        policy=resolve_triage_policy(cfg.triage, workdir),
        accounting=accounting)
    tacct = accounting.get("triage")
    if tacct:
        print("survey: triage %s: scored %d, folding %d (%d avoided)"
              % (tacct.get("mode"), tacct.get("scored", 0),
                 tacct.get("selected", len(top)),
                 tacct.get("folds_avoided", 0)))
    for i, c in enumerate(top):
        accpath = os.path.join(workdir, c.filename) \
            if not os.path.dirname(c.filename) else c.filename
        if c.path:
            accpath = os.path.join(c.path, c.filename)
        candfile = accpath + ".cand"
        datfile = accpath.split("_ACCEL_")[0] + ".dat"
        if seam is not None:
            # prepfold reads its series from disk: spill this one
            # trial from the seam on demand (a no-op when the durable
            # tier already wrote it)
            seam.ensure_dat(datfile)
        outbase = os.path.join(workdir, "fold_cand%d" % (i + 1))
        if _valid(manifest, outbase + ".pfd"):
            res.folded.append(outbase + ".pfd")
            continue
        try:
            prepfold_main(["-accelfile", candfile,
                           "-accelcand", str(c.candnum),
                           "-dm", "%.2f" % c.DM, "-nosearch",
                           "-o", outbase, datfile])
            res.folded.append(outbase + ".pfd")
            _record(manifest, [outbase + ".pfd"], "prepfold")
        except SystemExit as e:
            print("survey: fold of cand %d failed: %s" % (i + 1, e))
        _chaos(cfg, "fold-cand", obs)
    print("survey: folded %d candidates" % len(res.folded))

    timer.mark("single_pulse")
    _chaos(cfg, "pre-singlepulse", obs)
    # ---- 9. single-pulse search --------------------------------------
    if cfg.singlepulse and res.datfiles:
        from presto_tpu.apps.single_pulse_search import main as sp_main
        # seam trials were searched device-resident (stage 9a) and
        # their .singlepulse artifacts verify here; anything else goes
        # through the app — spilled from the seam first if its .dat
        # never hit disk.
        _drop_stale(manifest,
                    [f[:-4] + ".singlepulse" for f in res.datfiles])
        sp_todo = [f for f in res.datfiles
                   if not _valid(manifest, f[:-4] + ".singlepulse")]
        if seam is not None:
            for f in sp_todo:
                seam.ensure_dat(f)
            sp_todo = [f for f in sp_todo if os.path.exists(f)]
        if sp_todo:
            argv = ["-t", str(cfg.sp_threshold)]
            if cfg.sp_maxwidth:
                argv += ["-m", str(cfg.sp_maxwidth)]
            sp_main(argv + sp_todo)
            _record(manifest,
                    [f[:-4] + ".singlepulse" for f in sp_todo],
                    "singlepulse")
        from presto_tpu.search.singlepulse import read_singlepulse
        for f in res.datfiles:
            spf = f[:-4] + ".singlepulse"
            if os.path.exists(spf):
                res.sp_events += len(read_singlepulse(spf))
        print("survey: %d single-pulse events" % res.sp_events)
    _chaos(cfg, "post-survey", obs)

    return res


# ----------------------------------------------------------------------
# Stacked cross-job execution (the serve layer's batch executor)
# ----------------------------------------------------------------------

class StackedSeamError(RuntimeError):
    """This job set cannot share one stacked device chain (e.g. the
    seams hold mesh-sharded blocks, whose concatenation would cross
    device placements).  The serve scheduler treats it like any batch
    failure: degrade to the per-job path."""


class _FanTimer:
    """StageTimer fan-out: the merged device stage advances every
    stacked job's stage clock together (a shared device call IS each
    job's stage work; attributing it N ways would hide it from N-1
    of them)."""

    def __init__(self, timers):
        self.timers = [t for t in timers if t is not None]

    def mark(self, name):
        for t in self.timers:
            t.mark(name)


class _FanInjector:
    """Chaos fan-out for the merged chain: a fault injected into ANY
    stacked job must abort the shared device call (the scheduler then
    degrades the whole batch to per-job execution)."""

    def __init__(self, injectors):
        self.injectors = list(injectors)

    def point(self, name):
        for fi in self.injectors:
            fi.point(name)


class _StackManifest:
    """Artifact-journal fan-out for a merged seam: every record /
    verify routes to the manifest of the job whose workdir holds the
    path, so N stacked jobs' journals end up exactly what N per-job
    runs would have written."""

    def __init__(self, routes):
        #: [(abs workdir, manifest-or-None)], deepest path first so a
        #: nested workdir routes to its own journal
        self.routes = sorted(((os.path.abspath(w), m)
                              for w, m in routes),
                             key=lambda e: -len(e[0]))

    def _for(self, path):
        p = os.path.abspath(path)
        for wd, m in self.routes:
            if p == wd or p.startswith(wd + os.sep):
                return m
        return None

    def _grouped(self, paths):
        groups = {}
        for p in paths:
            m = self._for(p)
            groups.setdefault(id(m), (m, []))[1].append(p)
        return list(groups.values())

    def valid(self, path):
        m = self._for(path)
        return os.path.exists(path) if m is None else m.valid(path)

    def stage_of(self, path):
        m = self._for(path)
        return "" if m is None else m.stage_of(path)

    def record_many(self, paths, stage="", save=True):
        for m, ps in self._grouped(paths):
            if m is not None:
                m.record_many(ps, stage, save=save)

    def invalidate_stale(self, paths, remove=True):
        stale = []
        for m, ps in self._grouped(paths):
            if m is not None:
                stale += list(m.invalidate_stale(ps, remove=remove))
            else:
                # journal-less jobs keep the legacy contract: missing
                # files are simply not survivors
                stale += [p for p in ps if not os.path.exists(p)]
        return stale


def _merged_seam(ctxs, obs, manifest):
    """ONE StageSeam over every stacked job's deposited blocks:
    same-geometry blocks (equal padded length, valid span, and sample
    time) are concatenated on the batch axis — jobs stacked into one
    [sum(numdms), numout] device array — so the downstream FFT /
    accelsearch / single-pulse stages run one batched dispatch where
    N per-job runs paid N.  Per-trial math is independent of batch
    composition (the DM-sharded seam's pinned invariant), so every
    artifact byte matches the per-job run.  Source blocks hand their
    DEVICE reference to the merged copy (host copies stay with each
    job's own seam for spills and prepfold)."""
    import jax.numpy as jnp
    import numpy as np
    from presto_tpu.pipeline import fusion

    cfg0 = ctxs[0]["cfg"]
    seam = fusion.StageSeam(ctxs[0]["workdir"], durable=_durable(cfg0),
                            manifest=manifest, obs=obs,
                            inflight_depth=cfg0.inflight_depth)
    groups = {}
    order = []
    for c in ctxs:
        for b in c["seam"].blocks:
            if fusion.is_sharded(b):
                raise StackedSeamError(
                    "mesh-sharded seam blocks cannot be stacked "
                    "across jobs")
            key = (int(b.numout), int(b.valid), float(b.dt))
            if key not in groups:
                order.append(key)
            groups.setdefault(key, []).append(b)
    for key in order:
        blocks = groups[key]
        if len(blocks) == 1:
            mb = blocks[0]
        else:
            mb = fusion.SeamBlock(
                names=[n for b in blocks for n in b.names],
                infos=[i for b in blocks for i in b.infos],
                dms=[d for b in blocks for d in b.dms],
                series_dev=jnp.concatenate(
                    [b.series_dev for b in blocks], axis=0),
                series_host=np.concatenate(
                    [b.series_host for b in blocks], axis=0),
                valid=key[1], numout=key[0], dt=key[2])
            for b in blocks:
                # the merged copy owns the HBM now; each job's seam
                # keeps the bit-identical host copy for spills/folds
                b.series_dev = None
        seam.blocks.append(mb)
        for row, name in enumerate(mb.names):
            seam._by_dat[os.path.abspath(name + ".dat")] = (mb, row)
    return seam


def _stacked_device_stages(ctxs):
    """The merged middle for one sub-stack: every job's seam blocks
    concatenated, ONE _device_search_stages pass over the union."""
    from dataclasses import replace as _replace
    cfg0 = ctxs[0]["cfg"]
    obs0 = ctxs[0]["obs"]
    manifest = _StackManifest([(c["workdir"], c["manifest"])
                               for c in ctxs])
    injectors = [c["cfg"].fault_injector for c in ctxs
                 if c["cfg"].fault_injector is not None]
    cfg_m = cfg0
    if injectors and (len(injectors) > 1
                      or injectors[0] is not cfg0.fault_injector):
        cfg_m = _replace(cfg0, fault_injector=_FanInjector(injectors))
    seam = _merged_seam(ctxs, obs0, manifest)
    disk_only = [f for c in ctxs for f in c["disk_only"]]
    datfiles = [f for c in ctxs for f in c["res"].datfiles]
    timer = _FanTimer([c["timer"] for c in ctxs])
    _device_search_stages(seam, disk_only, datfiles, cfg_m,
                          cfg_m.all_passes, timer, manifest, obs0)


def run_survey_stacked(jobs, stack_planner=None):
    """Run N same-geometry surveys with the device-bound middle
    STACKED: per-job heads (rfifind -> DDplan -> prepsubband) deposit
    N seams, the merged DM fan-outs cross the rFFT -> (zap) ->
    accelsearch -> single-pulse chain in shared batched dispatches
    (one H2D already paid at dedisp time, one candidate-collection
    download per stacked chunk), and per-job tails (sift / fold /
    residual single-pulse) finish each survey.

    jobs: sequence of (rawfiles, cfg, workdir, timer) tuples whose
    configs are stack-compatible (serve/batchexec checks the full
    signature; the chain itself requires equal pass geometry).
    stack_planner: optional callable(per_job_chain_bytes: list[int])
    -> sub-stack sizes summing to N (serve/batchexec supplies the
    tuned max-stack x pad-bucket plan with the HBM-budget clamp);
    None = one stack spanning every job.

    Byte-identity invariant: stacking only widens the batch axis of
    dispatches whose per-trial math is independent (the invariant the
    DM-sharded seam already pins), so every artifact is byte-identical
    to N independent run_survey calls.  Any failure propagates to the
    caller — the serve scheduler's existing degradation path then
    redoes the batch per-job (the verify-not-trust resume contract
    makes the partial head work safe to redo).
    """
    from presto_tpu import tune as _tune
    from presto_tpu.io.atomic import cleanup_stale_tmp
    from presto_tpu.obs import resolve_obs
    from presto_tpu.utils.timing import StageTimer

    ctxs = []
    for (rawfiles, cfg, workdir, timer) in jobs:
        obs = resolve_obs(getattr(cfg, "obs", None))
        os.makedirs(workdir, exist_ok=True)
        rawfiles = [os.path.abspath(f) for f in rawfiles]
        base = os.path.join(
            workdir,
            os.path.splitext(os.path.basename(rawfiles[0]))[0])
        cleanup_stale_tmp(workdir)
        manifest = None
        if cfg.verify_resume:
            from presto_tpu.pipeline.manifest import SurveyManifest
            manifest = SurveyManifest.load(workdir)
        if timer is None:
            timer = StageTimer(obs=obs)
        ctxs.append({
            "rawfiles": rawfiles, "cfg": cfg, "workdir": workdir,
            "base": base, "res": SurveyResult(workdir=workdir),
            "timer": timer, "manifest": manifest, "obs": obs,
            "span": None, "result": None,
        })
    cfg0 = ctxs[0]["cfg"]
    try:
        with _tune.scoped(cfg0.tune):
            for c in ctxs:
                c["span"] = c["obs"].span(
                    "survey", workdir=c["workdir"],
                    raw=os.path.basename(c["rawfiles"][0]),
                    stacked=len(ctxs))
                c["seam"], c["disk_only"] = _survey_head(
                    c["rawfiles"], c["cfg"], c["workdir"], c["base"],
                    c["res"], c["timer"], c["manifest"], c["obs"])
            sizes = [len(ctxs)]
            if stack_planner is not None:
                per_job = [sum(len(b.names) * b.numout * 4 * 3
                               for b in c["seam"].blocks)
                           for c in ctxs]
                sizes = list(stack_planner(per_job)) or sizes
            if sum(sizes) != len(ctxs):
                raise StackedSeamError(
                    "stack plan %r does not cover %d jobs"
                    % (sizes, len(ctxs)))
            i = 0
            for size in sizes:
                _stacked_device_stages(ctxs[i:i + size])
                i += size
            for c in ctxs:
                c["timer"].mark("sift")
                _chaos(c["cfg"], "pre-sift", c["obs"])
                c["result"] = _finish_survey_stages(
                    c["rawfiles"], c["cfg"], c["workdir"], c["base"],
                    c["res"], c["timer"], c["manifest"], c["obs"],
                    seam=c["seam"])
                c["span"].finish()
                c["span"] = None
    except BaseException as e:
        for c in ctxs:
            if c["span"] is not None:
                c["span"].finish("error: %s" % type(e).__name__)
                c["span"] = None
            c["obs"].dump_flight(c["workdir"],
                                 reason=type(e).__name__)
        raise
    finally:
        for c in ctxs:
            c["timer"].mark(None)
            c["timer"].report()
            with _tune.scoped(c["cfg"].tune):
                _tune.write_provenance(c["workdir"])
            c["obs"].flush(default_dir=c["workdir"])
    return [c["result"] for c in ctxs]
