"""Cross-DM candidate sifting for acceleration-search output.

Reference: lib/python/sifting.py — collect *_ACCEL_<z> candidates over
all DM trials, reject implausible ones (period range, known birdies,
significance thresholds, rogue harmonic powers), collapse duplicates
across DMs into "hits" on the strongest detection, strip harmonics of
stronger fundamentals, and drop candidates whose DM behavior is wrong
(too few DM hits, peak at very low DM, gaps in the DM hit list — real
pulsars persist over a contiguous DM span peaking away from zero).

Candidate lists are tiny (thousands); this is pure host Python by
design, same as the reference.  The numerics differ only in sort
stability, not semantics.
"""

from __future__ import annotations

import os
import re
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Module-level defaults (sifting.py:20-37)
R_ERR = 1.1              # Fourier bin tolerance for "same" candidate
LONG_PERIOD = 15.0       # s
SHORT_PERIOD = 0.0005    # s
SIGMA_THRESHOLD = 6.0
C_POW_THRESHOLD = 100.0
HARM_POW_CUTOFF = 8.0

@dataclass
class SiftPolicy:
    """One survey's sifting thresholds — the knobs the reference's
    survey drivers set as sifting-module globals
    (PALFA_presto_search.py:47-52)."""
    sigma_threshold: float = SIGMA_THRESHOLD
    c_pow_threshold: float = C_POW_THRESHOLD
    short_period: float = SHORT_PERIOD
    long_period: float = LONG_PERIOD
    harm_pow_cutoff: float = HARM_POW_CUTOFF
    r_err: float = R_ERR


DM_RE = re.compile(r"DM(\d+\.\d{2})")


def default_known_birds_f() -> List[Tuple[float, float]]:
    """(freq, err) pairs from the shipped default birdie list
    (power-mains harmonics).  OPT-IN — pass the result as
    known_birds_f (e.g. ACCEL_sift -defaultbirds); the reference's
    ACCEL_sift recipe defaults to an empty birdie list, so the sift
    never rejects by default."""
    from presto_tpu.ops.rednoise import read_birds_bary
    from presto_tpu.utils.catalog import default_birds_path
    path = default_birds_path()
    if not path:
        return []
    return [(f, w) for (f, w, _b) in read_birds_bary(path)]

HARM_RATIOS = [(3, 2), (5, 2), (2, 3), (4, 3), (5, 3),
               (3, 4), (5, 4), (2, 5), (3, 5), (4, 5)]


@dataclass
class Candidate:
    """One accelsearch candidate (sifting.py:167-206)."""
    candnum: int
    sigma: float
    numharm: int
    ipow_det: float       # incoherent (summed) power
    cpow: float           # coherent power
    r: float              # Fourier bin of the fundamental
    z: float
    DMstr: str
    filename: str
    T: float
    harm_pows: Optional[np.ndarray] = None
    note: str = ""
    snr: float = 0.0
    hits: List[Tuple[float, float, float]] = field(default_factory=list)
    # each hit: (DM, snr, sigma)

    def __post_init__(self):
        self.path, self.filename = os.path.split(self.filename)
        self.DM = float(self.DMstr)
        self.f = self.r / self.T
        self.p = 1.0 / self.f if self.f > 0 else np.inf
        if not self.hits:
            self.hits = [(self.DM, self.snr, self.sigma)]

    def add_as_hit(self, other: "Candidate") -> None:
        self.hits.extend(other.hits)

    def harms_to_snr(self) -> None:
        """Approximate SNR from harmonic powers (sifting.py:200-205)."""
        amps = np.maximum(np.asarray(self.harm_pows, np.float64) - 1.0,
                          0.0)
        self.snr = float(np.sqrt(amps).sum())
        self.hits = [(self.DM, self.snr, self.sigma)]

    def __str__(self) -> str:
        cand = "%s:%d" % (self.filename, self.candnum)
        return ("%-65s   %7.2f  %6.2f  %6.2f  %s   %7.1f  %7.1f  "
                "%12.6f  %10.2f  %8.2f" %
                (cand, self.DM, self.snr, self.sigma,
                 ("%2d" % self.numharm).center(7), self.ipow_det,
                 self.cpow, self.p * 1000.0, self.r, self.z))


class Candlist:
    """Sift container (sifting.py:208-1097) with bad/dupe tracking."""

    def __init__(self, cands: Optional[List[Candidate]] = None):
        self.cands: List[Candidate] = list(cands) if cands else []
        self.badcands: Dict[str, List[Candidate]] = {}
        self.duplicates: List[Candidate] = []

    # -- container protocol -------------------------------------------
    def __len__(self):
        return len(self.cands)

    def __iter__(self):
        return iter(self.cands)

    def __getitem__(self, i):
        return self.cands[i]

    def __add__(self, other):
        out = Candlist(self.cands + other.cands)
        out.badcands = {k: list(v) for k, v in self.badcands.items()}
        for k, v in other.badcands.items():
            out.badcands.setdefault(k, []).extend(v)
        out.duplicates = self.duplicates + other.duplicates
        return out

    def extend(self, other):
        # carry rejected/duplicate candidates too, so aggregated lists
        # keep the full rejection bookkeeping (sifting.py semantics)
        self.cands.extend(other.cands)
        for k, v in other.badcands.items():
            self.badcands.setdefault(k, []).extend(v)
        self.duplicates.extend(other.duplicates)

    def sort_by_sigma(self):
        self.cands.sort(key=lambda c: (-c.sigma, -c.ipow_det))

    def _mark_bad(self, idx: int, why: str):
        self.badcands.setdefault(why, []).append(self.cands.pop(idx))

    # -- rejections (sifting.py:536-731) ------------------------------
    def reject_longperiod(self, long_period: float = LONG_PERIOD):
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if c.p > long_period:
                c.note = "period %.3f s > %.3f s" % (c.p, long_period)
                self._mark_bad(i, "longperiod")

    def reject_shortperiod(self, short_period: float = SHORT_PERIOD):
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if c.p < short_period:
                c.note = "period %.5g s < %.5g s" % (c.p, short_period)
                self._mark_bad(i, "shortperiod")

    def reject_knownbirds(self, known_birds_f: Sequence = (),
                          known_birds_p: Sequence = ()):
        """known_birds_f: (freq Hz, err Hz); known_birds_p: (ms, err)."""
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            bad = False
            for bird, err in known_birds_f:
                if abs(c.f - bird) < err:
                    c.note = "freq matches birdie %.6g Hz" % bird
                    bad = True
                    break
            if not bad:
                for bird, err in known_birds_p:
                    if abs(c.p * 1000.0 - bird) < err:
                        c.note = "period matches birdie %.6g ms" % bird
                        bad = True
                        break
            if bad:
                self._mark_bad(i, "knownbirds")

    def reject_threshold(self, sigma_threshold: float = SIGMA_THRESHOLD,
                         c_pow_threshold: float = C_POW_THRESHOLD):
        """Single-harmonic cands may pass on coherent power alone
        (sifting.py:620-659)."""
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if c.numharm == 1:
                if c.sigma < sigma_threshold and c.cpow < c_pow_threshold:
                    c.note = "sigma %.2f and cpow %.1f below thresholds" \
                        % (c.sigma, c.cpow)
                    self._mark_bad(i, "threshold")
            elif c.sigma < sigma_threshold:
                c.note = "sigma %.2f below threshold" % c.sigma
                self._mark_bad(i, "threshold")

    def reject_harmpowcutoff(self,
                             harm_pow_cutoff: float = HARM_POW_CUTOFF):
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if c.harm_pows is None or not len(c.harm_pows):
                continue
            if float(np.max(c.harm_pows)) < harm_pow_cutoff:
                c.note = "all harmonics below power %g" % harm_pow_cutoff
                self._mark_bad(i, "harmpowcutoff")

    def reject_rogueharmpow(self):
        """Drop cands dominated by a single high-numbered harmonic
        (sifting.py:681-715)."""
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if c.harm_pows is None or len(c.harm_pows) < 2:
                continue
            maxharm = int(np.argmax(c.harm_pows))
            maxpow = float(c.harm_pows[maxharm])
            sorted_pows = np.sort(np.asarray(c.harm_pows, np.float64))
            rest = float(sorted_pows[:-1].sum())
            if ((c.numharm >= 8 and maxharm > 4 and maxpow > 2 * rest)
                    or (c.numharm >= 4 and maxharm > 2
                        and maxpow > 3 * rest)):
                c.note = "dominated by harmonic %d" % (maxharm + 1)
                self._mark_bad(i, "rogueharmpow")

    def default_rejection(self, known_birds_f=(), known_birds_p=(),
                          policy: "SiftPolicy" = None):
        pol = policy or SiftPolicy()
        self.reject_longperiod(pol.long_period)
        self.reject_shortperiod(pol.short_period)
        self.reject_knownbirds(known_birds_f, known_birds_p)
        self.reject_threshold(pol.sigma_threshold, pol.c_pow_threshold)
        self.reject_harmpowcutoff(pol.harm_pow_cutoff)
        self.reject_rogueharmpow()

    # -- dedup / harmonic / DM sifts ----------------------------------
    def remove_duplicate_candidates(self, r_err: float = R_ERR):
        """Collapse same-r detections across DMs onto the strongest,
        recording the others as hits (sifting.py:732-791)."""
        self.cands.sort(key=lambda c: c.r)
        ii = 0
        while ii < len(self.cands):
            jj = ii + 1
            while (jj < len(self.cands)
                   and abs(self.cands[ii].r - self.cands[jj].r) < r_err):
                jj += 1
            if jj == ii + 1:
                ii += 1
                continue
            matches = self.cands[ii:jj]
            best = max(matches, key=lambda c: (c.sigma, c.ipow_det))
            for m in matches:
                if m is best:
                    continue
                best.add_as_hit(m)
                m.note = "duplicate of %s:%d" % (best.filename,
                                                 best.candnum)
                self.duplicates.append(m)
            self.cands[ii:jj] = [best]
            # best may still collect more matches; don't advance
            # (sifting.py:783-786)
        self.sort_by_sigma()

    def remove_harmonics(self, r_err: float = R_ERR):
        """Drop weaker candidates that are integer or simple-ratio
        harmonics of stronger ones (sifting.py:793-881)."""
        if not self.cands:
            return
        self.sort_by_sigma()
        f_err0 = r_err / self.cands[0].T
        ii = 0
        while ii < len(self.cands) - 1:
            fund = self.cands[ii]
            jj = len(self.cands) - 1
            while jj > ii:
                harm = self.cands[jj]
                zap, harmstr = False, ""
                for factor in range(1, 17):
                    if abs(fund.f - harm.f * factor) < f_err0 * factor:
                        zap, harmstr = True, "1/%d" % factor
                        break
                    if abs(fund.f - harm.f / factor) < f_err0 / factor:
                        zap, harmstr = True, "%d" % factor
                        break
                if not zap:
                    for numer, denom in HARM_RATIOS:
                        factor = numer / denom
                        if abs(fund.f - harm.f * factor) < f_err0 * factor:
                            zap, harmstr = True, "%d/%d" % (denom, numer)
                            break
                if zap:
                    harm.note = ("harmonic (%s) of %s:%d"
                                 % (harmstr, fund.filename, fund.candnum))
                    self._mark_bad(jj, "harmonic")
                jj -= 1
            ii += 1

    def remove_DM_problems(self, numdms: int, dmlist: Sequence[float],
                           low_DM_cutoff: float):
        """Reject cands with too few DM hits, peak at very low DM, or
        gaps in the DM hit sequence (sifting.py:883-966)."""
        dms = np.unique(np.asarray([float(d) for d in dmlist]))
        dmdict = {"%.2f" % d: i for i, d in enumerate(dms)}
        self.sort_by_sigma()
        for i in reversed(range(len(self.cands))):
            c = self.cands[i]
            if len(c.hits) < numdms:
                c.note = "only %d DM hits (< %d)" % (len(c.hits), numdms)
                self._mark_bad(i, "dmproblem")
                continue
            imax = int(np.argmax([h[2] for h in c.hits]))
            if float(c.hits[imax][0]) <= low_DM_cutoff:
                c.note = "peak sigma at DM %.2f <= cutoff %.2f" % (
                    c.hits[imax][0], low_DM_cutoff)
                self._mark_bad(i, "dmproblem")
                continue
            if len(c.hits) > 1:
                idx = np.sort([dmdict["%.2f" % h[0]] for h in c.hits])
                if int(np.min(np.diff(idx))) > 1:
                    c.note = "gaps in the DM hit list"
                    self._mark_bad(i, "dmproblem")

    # -- reporting ----------------------------------------------------
    def summary_lines(self) -> List[str]:
        lines = ["#" + "file:candnum".center(66) + "DM".center(9)
                 + "SNR".center(8) + "sigma".center(8)
                 + "numharm".center(9) + "ipow".center(9)
                 + "cpow".center(9) + "P(ms)".center(14)
                 + "r".center(12) + "z".center(8)]
        for c in self.cands:
            lines.append(str(c))
        return lines

    def to_file(self, path: str):
        from presto_tpu.io.atomic import atomic_open
        with atomic_open(path, "w") as f:
            f.write("\n".join(self.summary_lines()) + "\n")
            for c in self.cands:
                for dm, snr, sig in sorted(c.hits):
                    f.write("  DM=%6.2f SNR=%5.2f Sigma=%5.2f\n"
                            % (dm, snr, sig))


# ----------------------------------------------------------------------
# Reading our accelsearch artifacts
# ----------------------------------------------------------------------

def candlist_from_accelfile(filename: str) -> Candlist:
    """Parse one *_ACCEL_<z> text file written by
    presto_tpu.apps.accelsearch.write_accel_file."""
    from presto_tpu.io.infodata import read_inf
    base = filename[:filename.rfind("_ACCEL")]
    info = read_inf(base)
    T = float(info.N) * info.dt
    m = DM_RE.search(filename)
    dmstr = m.group(1) if m else "%.2f" % info.dm
    cands = []
    with open(filename) as f:
        lines = f.readlines()[3:]
    for line in lines:
        if not line.strip() or not line[0].isdigit():
            continue
        parts = line.split()
        candnum = int(parts[0])
        sigma = float(parts[1])
        ipow = float(parts[2])
        cpow = float(parts[3])
        numharm = int(parts[4])
        r = float(parts[7])
        z = float(parts[9])
        c = Candidate(candnum=candnum, sigma=sigma, numharm=numharm,
                      ipow_det=ipow, cpow=cpow, r=r, z=z, DMstr=dmstr,
                      filename=filename, T=T)
        c.snr = np.sqrt(max(ipow - numharm, 0.0))
        c.hits = [(c.DM, c.snr, c.sigma)]
        cands.append(c)
    return Candlist(cands)


def read_candidates(filenames: Sequence[str],
                    prelim_reject: bool = True,
                    known_birds_f=(), known_birds_p=(),
                    policy: "SiftPolicy" = None) -> Candlist:
    """Aggregate candidates over many DM trials
    (sifting.py:1203-1230).

    Ingestion order is made deterministic here — the file list is
    sorted before reading — because exact-tie resolution in the
    duplicate/harmonic sifts follows encounter order: a glob whose
    order depends on the filesystem would make the sifted list (and
    therefore a discovery DAG's fold fan-out set) differ across
    hosts byte-for-byte identical inputs."""
    out = Candlist()
    for fn in sorted(filenames):
        cl = candlist_from_accelfile(fn)
        if prelim_reject:
            cl.default_rejection(known_birds_f, known_birds_p, policy)
        out.extend(cl)
    return out


def select_fold_candidates(cl: Candlist, fold_top: int = 3,
                           fold_sigma: Optional[float] = None,
                           max_folds: int = 150,
                           max_folds_per_pass: Optional[tuple] = None,
                           pass_zmaxes: Sequence[int] = (),
                           policy=None,
                           accounting: Optional[dict] = None
                           ) -> List[Candidate]:
    """The survey drivers' fold-selection policy, factored so the
    batch survey (pipeline/survey.py) and the discovery-DAG sift /
    triage nodes (serve/dag.py) fan out the SAME candidates.

    With ``fold_sigma`` set: fold everything at or above it, capped at
    ``max_folds`` — or, with ``max_folds_per_pass``, capped per accel
    pass (aligned with ``pass_zmaxes``, e.g. GBNCC's 20-lo + 10-hi
    split).  Otherwise: the top ``fold_top`` by sigma.

    ``policy`` is the opt-in triage seam: a callable
    ``policy(selected, cl, accounting) -> selected`` (e.g.
    `triage.TriagePolicy`) applied to the heuristic selection.  A
    policy may only reorder/drop — it sees the heuristic result, so
    every survivor folds with exactly the parameters an untriaged
    run would use.  ``None`` (the default) is the byte-stable
    heuristic path.

    ``accounting``, when a dict is passed, is filled with selection
    bookkeeping: ``above_sigma``, ``selected``, and — the per-pass
    trap this signature grew around — ``untagged_dropped``, the
    above-sigma candidates whose filename matched NO ``_ACCEL_<z>``
    pass tag and which the per-pass caps therefore silently excluded
    (also surfaced as a RuntimeWarning)."""
    ranked = sorted(cl.cands, key=lambda c: -c.sigma)
    acct = accounting if accounting is not None else {}
    acct.setdefault("untagged_dropped", 0)
    acct.setdefault("untagged", [])
    if fold_sigma is not None:
        above = [c for c in ranked if c.sigma >= fold_sigma]
        acct["above_sigma"] = len(above)
        if max_folds_per_pass:
            if len(max_folds_per_pass) != len(pass_zmaxes):
                raise ValueError(
                    "max_folds_per_pass has %d caps for %d accel "
                    "passes" % (len(max_folds_per_pass),
                                len(pass_zmaxes)))
            tags = tuple("_ACCEL_%d" % z for z in pass_zmaxes)
            untagged = [c for c in above
                        if not any(c.filename.endswith(t)
                                   for t in tags)]
            if untagged:
                # historically a SILENT drop: an above-sigma
                # candidate from a pass the caps don't name (stale
                # pass_zmaxes, a renamed ACCEL table) simply never
                # folded.  The exclusion stands (the caps define the
                # budget) but it is now counted and surfaced.
                acct["untagged_dropped"] = len(untagged)
                acct["untagged"] = [
                    (c.filename, c.candnum, c.sigma)
                    for c in untagged]
                warnings.warn(
                    "select_fold_candidates: %d above-sigma "
                    "candidate(s) match no _ACCEL_<zmax> pass tag "
                    "(passes %s) and are excluded from the per-pass "
                    "fold caps — first: %s:%d (sigma %.2f)"
                    % (len(untagged),
                       list(pass_zmaxes), untagged[0].filename,
                       untagged[0].candnum, untagged[0].sigma),
                    RuntimeWarning, stacklevel=2)
            top = []
            for tag, cap in zip(tags, max_folds_per_pass):
                top += [c for c in above
                        if c.filename.endswith(tag)][:cap]
        else:
            top = above[:max_folds]
    else:
        acct["above_sigma"] = len(ranked)
        top = ranked[:fold_top]
    acct["selected"] = len(top)
    if policy is not None:
        top = policy(top, cl, acct)
        acct["selected"] = len(top)
    return top


def sift_candidates(filenames: Sequence[str], numdms_min: int = 2,
                    low_DM_cutoff: float = 2.0,
                    known_birds_f=(), known_birds_p=(),
                    r_err: float = None,
                    policy: "SiftPolicy" = None) -> Candlist:
    """The ACCEL_sift.py recipe (python/ACCEL_sift.py:40-76):
    read -> reject -> dedup across DMs -> DM checks -> harmonics.
    An explicit r_err beats the policy's; default R_ERR otherwise."""
    if r_err is None:
        r_err = policy.r_err if policy is not None else R_ERR
    cl = read_candidates(filenames, True, known_birds_f, known_birds_p,
                         policy)
    dmlist = sorted({c.DMstr for c in cl})
    cl.remove_duplicate_candidates(r_err)
    if len(dmlist) > 1:
        cl.remove_DM_problems(numdms_min, dmlist, low_DM_cutoff)
    cl.remove_harmonics(r_err)
    cl.sort_by_sigma()
    return cl
