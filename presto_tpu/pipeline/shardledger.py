"""Per-survey DM-shard ledger (the redo unit for elastic clusters).

PR 2's manifest journal made single-host resume *verify instead of
trust*; this ledger extends the same semantics to the unit a cluster
loses when a member dies: a **DM shard** (a contiguous run of DM-trial
rows).  The reference's mpiprepsubband statically partitions the DM
axis across MPI ranks (SURVEY §4.8) so a lost rank loses its rows
forever; here every shard is a *leased* row in `shards.json`, and any
surviving host can re-lease and recompute a dead member's rows because
each shard's computation is deterministic given its spec.

The lease / heartbeat / epoch-fencing / staged-commit mechanics are
the generic `pipeline/leaseledger.LeaseLedger` (shared with the fleet
job ledger, `serve/jobledger.py`); this module binds them to the
DM-shard vocabulary: the `shards.json` schema, the `shard-*`
flight-recorder events, and the `(shard_id, row_lo, row_hi)` specs of
`make_dm_shards`.  See the leaseledger docstring for the state
machine and the zombie-write fence.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from presto_tpu.pipeline.leaseledger import (DONE, LEASED,  # noqa: F401
                                             HEARTBEAT_PREFIX,
                                             PENDING, LeaseLedger,
                                             LedgerError, ReapReport,
                                             StaleLeaseError)

LEDGER_NAME = "shards.json"


class ShardLedgerError(LedgerError):
    """Base class for shard-ledger protocol violations."""


class StaleEpochError(StaleLeaseError, ShardLedgerError):
    """A write attempted under a lease the cluster has fenced off —
    the zombie-worker case.  The staged outputs were discarded."""

    def __init__(self, shard_id: str, host: str, epoch: int,
                 current_epoch: int, why: str):
        super().__init__(shard_id, host, epoch, current_epoch, why)
        self.shard_id = shard_id


@dataclass
class Lease:
    """A granted shard lease (what the worker computes against)."""
    shard_id: str
    rows: Tuple[int, int]          # [lo, hi) DM-row indices
    epoch: int                     # fence token for complete()
    expires: float

    @property
    def item_id(self) -> str:      # generic-ledger lease protocol
        return self.shard_id


class ShardLedger(LeaseLedger):
    """Leased-shard journal for one survey working directory.

    Every public mutator is transactional: it takes the lock, reloads
    the ledger from disk, applies the change, and writes the whole
    file back atomically — so concurrent hosts always act on the
    latest accepted state and a kill mid-mutation loses nothing but
    that mutation.
    """

    LEDGER_NAME = LEDGER_NAME
    ITEMS_KEY = "shards"
    ERROR = ShardLedgerError
    STALE = StaleEpochError
    EV_LEASE = "shard-lease"
    EV_DONE = "shard-done"
    EV_REDO = "shard-redo"
    EV_STALE = "stale-write-rejected"
    EV_HOST_DEAD = "host-dead"
    EV_EPOCH_BUMP = "epoch-bump"

    # -- shard bookkeeping --------------------------------------------
    def ensure_shards(self, specs: Sequence[Tuple[str, int, int]],
                      meta: Optional[dict] = None) -> int:
        """Idempotently create shard rows.  `specs` is a sequence of
        (shard_id, row_lo, row_hi).  Existing rows keep their state
        (that is the resume contract); returns the pending count."""
        return self.ensure_items(
            [(sid, {"rows": [int(lo), int(hi)]})
             for sid, lo, hi in specs], meta=meta)

    def _make_lease(self, item_id: str, row: dict,
                    epoch: int) -> Lease:
        return Lease(item_id, tuple(row["rows"]), epoch,
                     float(row["lease_expires"]))


def make_dm_shards(numdms: int, shard_rows: int,
                   prefix: str = "dm") -> List[Tuple[str, int, int]]:
    """Split the DM axis [0, numdms) into ledger shard specs of up to
    `shard_rows` rows each."""
    if numdms <= 0:
        return []
    shard_rows = max(1, int(shard_rows))
    return [("%s%04d" % (prefix, i // shard_rows),
             i, min(i + shard_rows, numdms))
            for i in range(0, numdms, shard_rows)]
