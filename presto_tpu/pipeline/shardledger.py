"""Per-survey DM-shard ledger (the redo unit for elastic clusters).

PR 2's manifest journal made single-host resume *verify instead of
trust*; this ledger extends the same semantics to the unit a cluster
loses when a member dies: a **DM shard** (a contiguous run of DM-trial
rows).  The reference's mpiprepsubband statically partitions the DM
axis across MPI ranks (SURVEY §4.8) so a lost rank loses its rows
forever; here every shard is a *leased* row in `shards.json`, and any
surviving host can re-lease and recompute a dead member's rows because
each shard's computation is deterministic given its spec.

State machine per shard::

    pending --lease--> leased --complete--> done
       ^                 |                   |
       |   (lease expiry, owner death,      | (artifact fails
       |    explicit fail)                  |  size+CRC verify)
       +---------------- reap --------------+

Epoch fencing: the ledger carries a cluster **epoch**, bumped whenever
membership changes (a host misses its heartbeat, a lease is reaped).
Every lease records the epoch it was granted under; `complete()` is
accepted only when the shard is still leased to that owner *under that
epoch*.  A zombie worker — one declared dead whose process lingers —
therefore cannot land a late write: its lease was re-admitted at the
bump, the fence check fails, and its staged output files are deleted
before they can replace a journaled artifact.

Staged commits: workers never write final artifact names directly.
They stage outputs next to the targets (atomic temp writes) and hand
the staged map to `complete()`, which performs fence-check -> rename
-> size+CRC journal *under the ledger lock* — so a final artifact name
only ever holds bytes whose provenance the ledger accepted.

Cross-host coordination is plain shared-filesystem: the ledger file is
written atomically under a lock directory, and heartbeats are small
per-host files (`.hb-<host>.json`) so a 1 Hz heartbeat never contends
with the ledger lock.
"""

from __future__ import annotations

import contextlib
import errno
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from presto_tpu.io.atomic import atomic_write_text, file_checksum

LEDGER_NAME = "shards.json"
HEARTBEAT_PREFIX = ".hb-"

PENDING, LEASED, DONE = "pending", "leased", "done"


class ShardLedgerError(Exception):
    """Base class for ledger protocol violations."""


class StaleEpochError(ShardLedgerError):
    """A write attempted under a lease the cluster has fenced off —
    the zombie-worker case.  The staged outputs were discarded."""

    def __init__(self, shard_id: str, host: str, epoch: int,
                 current_epoch: int, why: str):
        self.shard_id = shard_id
        self.host = host
        self.epoch = epoch
        self.current_epoch = current_epoch
        self.why = why
        super().__init__(
            "stale write rejected: shard %r by %r under epoch %d "
            "(cluster epoch %d): %s"
            % (shard_id, host, epoch, current_epoch, why))


@dataclass
class Lease:
    """A granted shard lease (what the worker computes against)."""
    shard_id: str
    rows: Tuple[int, int]          # [lo, hi) DM-row indices
    epoch: int                     # fence token for complete()
    expires: float


@dataclass
class ReapReport:
    """What one reap pass changed."""
    dead_hosts: List[str] = field(default_factory=list)
    redone: List[str] = field(default_factory=list)
    epoch: int = 0
    bumped: bool = False


class _LockDir:
    """Tiny cross-process mutex: os.mkdir is atomic on POSIX.  A lock
    older than `stale` seconds is presumed abandoned by a killed
    process and broken — safe here because every mutation under the
    lock ends in an atomic whole-file replace, so a breaker can never
    observe a half-written ledger."""

    def __init__(self, path: str, timeout: float = 30.0,
                 stale: float = 30.0, poll: float = 0.02):
        self.path = path
        self.timeout = timeout
        self.stale = stale
        self.poll = poll

    @contextlib.contextmanager
    def __call__(self):
        deadline = time.time() + self.timeout
        while True:
            try:
                os.mkdir(self.path)
                break
            except OSError as e:
                if e.errno != errno.EEXIST:
                    raise
                try:
                    age = time.time() - os.path.getmtime(self.path)
                except OSError:
                    continue               # raced with the releaser
                if age > self.stale:
                    with contextlib.suppress(OSError):
                        os.rmdir(self.path)
                    continue
                if time.time() > deadline:
                    raise ShardLedgerError(
                        "could not acquire ledger lock %s within %.1fs"
                        % (self.path, self.timeout))
                time.sleep(self.poll)
        try:
            yield
        finally:
            with contextlib.suppress(OSError):
                os.rmdir(self.path)


class ShardLedger:
    """Leased-shard journal for one survey working directory.

    Every public mutator is transactional: it takes the lock, reloads
    the ledger from disk, applies the change, and writes the whole
    file back atomically — so concurrent hosts always act on the
    latest accepted state and a kill mid-mutation loses nothing but
    that mutation.
    """

    def __init__(self, workdir: str, name: str = LEDGER_NAME,
                 obs=None):
        self.workdir = os.path.abspath(workdir)
        self.path = os.path.join(self.workdir, name)
        self._lock = _LockDir(self.path + ".lock")
        self.obs = obs

    # -- raw state ----------------------------------------------------
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                state = json.load(f)
            if not isinstance(state, dict):
                raise ValueError("ledger is not an object")
        except (OSError, ValueError):
            state = {}
        state.setdefault("version", 1)
        state.setdefault("epoch", 0)
        state.setdefault("shards", {})
        state.setdefault("hosts", {})
        return state

    def _save(self, state: dict) -> None:
        atomic_write_text(self.path, json.dumps(
            state, indent=1, sort_keys=True) + "\n")

    def read(self) -> dict:
        """Lock-free snapshot (monitoring / tests)."""
        return self._load()

    @property
    def epoch(self) -> int:
        return int(self._load()["epoch"])

    # -- event plumbing ----------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self.obs is not None and getattr(self.obs, "enabled",
                                            False):
            self.obs.event(kind, **fields)

    # -- membership ---------------------------------------------------
    def join(self, host: str, addr: Optional[str] = None,
             now: Optional[float] = None) -> int:
        """Register (or re-register) a host; returns the epoch it
        joins under.  A host re-joining after being declared dead is
        admitted at the current epoch — its fenced leases were already
        re-admitted, so it simply starts fresh."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            state["hosts"][host] = {"joined": now, "alive": True,
                                    "addr": addr,
                                    "epoch": int(state["epoch"])}
            self._save(state)
            return int(state["epoch"])

    def heartbeat_path(self, host: str) -> str:
        return os.path.join(self.workdir, HEARTBEAT_PREFIX + host
                            + ".json")

    def heartbeat(self, host: str, epoch: int,
                  now: Optional[float] = None) -> None:
        """Cheap liveness signal: one small atomic file per host, no
        ledger lock taken."""
        now = time.time() if now is None else now
        atomic_write_text(self.heartbeat_path(host), json.dumps(
            {"host": host, "ts": now, "epoch": int(epoch)}) + "\n")

    def last_heartbeat(self, host: str) -> Optional[float]:
        try:
            with open(self.heartbeat_path(host)) as f:
                return float(json.load(f)["ts"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def alive_hosts(self, now: Optional[float] = None,
                    ttl: float = 15.0) -> List[str]:
        now = time.time() if now is None else now
        state = self._load()
        out = []
        for host, h in sorted(state["hosts"].items()):
            if not h.get("alive", False):
                continue
            hb = self.last_heartbeat(host)
            seen = hb if hb is not None else float(h.get("joined", 0))
            if now - seen <= ttl:
                out.append(host)
        return out

    # -- shard bookkeeping --------------------------------------------
    def ensure_shards(self, specs: Sequence[Tuple[str, int, int]],
                      meta: Optional[dict] = None) -> int:
        """Idempotently create shard rows.  `specs` is a sequence of
        (shard_id, row_lo, row_hi).  Existing rows keep their state
        (that is the resume contract); returns the pending count."""
        with self._lock():
            state = self._load()
            if meta:
                state.setdefault("meta", {}).update(meta)
            for sid, lo, hi in specs:
                state["shards"].setdefault(sid, {
                    "rows": [int(lo), int(hi)],
                    "state": PENDING,
                    "owner": None,
                    "lease_epoch": None,
                    "lease_expires": None,
                    "artifacts": {},
                    "redos": 0,
                })
            pending = sum(1 for s in state["shards"].values()
                          if s["state"] != DONE)
            self._save(state)
            return pending

    def lease(self, host: str, ttl: float,
              now: Optional[float] = None) -> Optional[Lease]:
        """Claim the first pending shard for `host`; None when no
        shard is currently pending (all leased or done)."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            h = state["hosts"].get(host)
            if h is not None and not h.get("alive", True):
                # false-positive death (slow heartbeat): rejoin at the
                # current epoch and carry on
                h["alive"] = True
                h["epoch"] = int(state["epoch"])
            for sid in sorted(state["shards"]):
                sh = state["shards"][sid]
                if sh["state"] != PENDING:
                    continue
                sh["state"] = LEASED
                sh["owner"] = host
                sh["lease_epoch"] = int(state["epoch"])
                sh["lease_expires"] = now + ttl
                self._save(state)
                self._event("shard-lease", shard=sid, host=host,
                            epoch=int(state["epoch"]))
                return Lease(sid, tuple(sh["rows"]),
                             int(state["epoch"]),
                             float(sh["lease_expires"]))
            self._save(state)
            return None

    def renew(self, lease: Lease, host: str, ttl: float,
              now: Optional[float] = None) -> bool:
        """Extend a held lease (long shards).  False when the lease
        was fenced off meanwhile."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            sh = state["shards"].get(lease.shard_id)
            if (sh is None or sh["state"] != LEASED
                    or sh["owner"] != host
                    or int(sh["lease_epoch"]) != int(lease.epoch)):
                return False
            sh["lease_expires"] = now + ttl
            self._save(state)
            return True

    def complete(self, lease: Lease, host: str,
                 staged: Dict[str, str],
                 now: Optional[float] = None) -> Dict[str, dict]:
        """Commit a computed shard: fence-check, rename each staged
        file onto its final path, journal size+CRC — all under the
        ledger lock.  `staged` maps final absolute path -> staged
        temp path.  Raises StaleEpochError (after deleting the staged
        files) when the lease was fenced off; a journaled artifact is
        then never overwritten."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            sh = state["shards"].get(lease.shard_id)
            why = None
            if sh is None:
                why = "unknown shard"
            elif sh["state"] != LEASED:
                why = "shard is %s, not leased" % sh["state"]
            elif sh["owner"] != host:
                why = "lease owned by %r" % sh["owner"]
            elif int(sh["lease_epoch"]) != int(lease.epoch):
                why = ("lease epoch %s superseded"
                       % sh["lease_epoch"])
            if why is not None:
                for tmp in staged.values():
                    with contextlib.suppress(OSError):
                        os.remove(tmp)
                self._event("stale-write-rejected",
                            shard=lease.shard_id, host=host,
                            epoch=int(lease.epoch),
                            cluster_epoch=int(state["epoch"]),
                            why=why)
                raise StaleEpochError(lease.shard_id, host,
                                      int(lease.epoch),
                                      int(state["epoch"]), why)
            arts: Dict[str, dict] = {}
            for final, tmp in sorted(staged.items()):
                os.replace(tmp, final)
                rel = os.path.relpath(os.path.abspath(final),
                                      self.workdir)
                arts[rel] = {"size": os.path.getsize(final),
                             "checksum": file_checksum(final)}
            sh["state"] = DONE
            sh["owner"] = host
            sh["lease_epoch"] = None
            sh["lease_expires"] = None
            sh["artifacts"] = arts
            sh["completed_epoch"] = int(state["epoch"])
            sh["completed_at"] = now
            self._save(state)
            self._event("shard-done", shard=lease.shard_id,
                        host=host, artifacts=len(arts))
            return arts

    def fail(self, lease: Lease, host: str) -> None:
        """Voluntarily release a held lease back to pending (compute
        error on this host; let another host try)."""
        with self._lock():
            state = self._load()
            sh = state["shards"].get(lease.shard_id)
            if (sh is not None and sh["state"] == LEASED
                    and sh["owner"] == host
                    and int(sh["lease_epoch"]) == int(lease.epoch)):
                self._readmit(sh)
                self._save(state)
                self._event("shard-redo", shard=lease.shard_id,
                            why="released", host=host)

    def readmit_owned(self, host: str) -> List[str]:
        """Re-admit every lease held by `host` — called by a
        *restarting* host on join (a fresh incarnation cannot have
        in-flight work, so any lease under its name is a dead one).
        Bumps the epoch when anything was re-admitted, fencing off the
        dead incarnation's possible late writes."""
        redone = []
        with self._lock():
            state = self._load()
            for sid in sorted(state["shards"]):
                sh = state["shards"][sid]
                if sh["state"] == LEASED and sh["owner"] == host:
                    self._readmit(sh)
                    redone.append(sid)
            if redone:
                state["epoch"] = int(state["epoch"]) + 1
            self._save(state)
        for sid in redone:
            self._event("shard-redo", shard=sid, why="owner-restart",
                        host=host)
        return redone

    @staticmethod
    def _readmit(sh: dict) -> None:
        sh["state"] = PENDING
        sh["owner"] = None
        sh["lease_epoch"] = None
        sh["lease_expires"] = None
        sh["redos"] = int(sh.get("redos", 0)) + 1

    # -- failure detection / redo -------------------------------------
    def reap(self, heartbeat_ttl: float,
             now: Optional[float] = None) -> ReapReport:
        """One failure-detection pass: mark hosts with stale
        heartbeats dead, re-admit their leases plus any lease past
        expiry, bump the epoch when anything changed.  Safe to call
        from every host (idempotent under the lock)."""
        now = time.time() if now is None else now
        report = ReapReport()
        with self._lock():
            state = self._load()
            for host, h in sorted(state["hosts"].items()):
                if not h.get("alive", False):
                    continue
                hb = self.last_heartbeat(host)
                seen = hb if hb is not None else float(
                    h.get("joined", 0))
                if now - seen > heartbeat_ttl:
                    h["alive"] = False
                    report.dead_hosts.append(host)
            dead = {host for host, h in state["hosts"].items()
                    if not h.get("alive", False)}
            for sid in sorted(state["shards"]):
                sh = state["shards"][sid]
                if sh["state"] != LEASED:
                    continue
                expired = (sh["lease_expires"] is not None
                           and now > float(sh["lease_expires"]))
                if sh["owner"] in dead or expired:
                    self._readmit(sh)
                    report.redone.append(sid)
            if report.dead_hosts or report.redone:
                state["epoch"] = int(state["epoch"]) + 1
                report.bumped = True
            report.epoch = int(state["epoch"])
            self._save(state)
        for host in report.dead_hosts:
            self._event("host-dead", host=host, epoch=report.epoch)
        for sid in report.redone:
            self._event("shard-redo", shard=sid, why="reaped",
                        epoch=report.epoch)
        if report.bumped:
            self._event("epoch-bump", epoch=report.epoch,
                        dead=report.dead_hosts, redone=report.redone)
        return report

    def verify_done(self) -> List[str]:
        """Verify-not-trust for completed shards: any done shard whose
        journaled artifacts are missing, resized, or checksum-stale on
        disk is re-admitted (its stale files are deleted so nothing
        can resurrect them).  Returns the re-admitted shard ids."""
        redone = []
        with self._lock():
            state = self._load()
            for sid in sorted(state["shards"]):
                sh = state["shards"][sid]
                if sh["state"] != DONE:
                    continue
                ok = True
                for rel, ent in sh.get("artifacts", {}).items():
                    p = os.path.join(self.workdir, rel)
                    if (not os.path.exists(p)
                            or os.path.getsize(p) != ent.get("size")
                            or file_checksum(p) != ent.get(
                                "checksum")):
                        ok = False
                        break
                if ok:
                    continue
                for rel in sh.get("artifacts", {}):
                    with contextlib.suppress(OSError):
                        os.remove(os.path.join(self.workdir, rel))
                sh["artifacts"] = {}
                self._readmit(sh)
                redone.append(sid)
            self._save(state)
        for sid in redone:
            self._event("shard-redo", shard=sid, why="verify-failed")
        return redone

    # -- progress -----------------------------------------------------
    def counts(self) -> Dict[str, int]:
        state = self._load()
        out = {PENDING: 0, LEASED: 0, DONE: 0}
        for sh in state["shards"].values():
            out[sh["state"]] = out.get(sh["state"], 0) + 1
        return out

    def all_done(self) -> bool:
        state = self._load()
        shards = state["shards"]
        return bool(shards) and all(s["state"] == DONE
                                    for s in shards.values())

    def redo_set(self, heartbeat_ttl: float,
                 now: Optional[float] = None) -> List[str]:
        """The shards a reap pass *would* re-admit right now (dead
        owners or expired leases) — computed without mutating."""
        now = time.time() if now is None else now
        state = self._load()
        dead = set()
        for host, h in state["hosts"].items():
            if not h.get("alive", False):
                dead.add(host)
                continue
            hb = self.last_heartbeat(host)
            seen = hb if hb is not None else float(h.get("joined", 0))
            if now - seen > heartbeat_ttl:
                dead.add(host)
        out = []
        for sid in sorted(state["shards"]):
            sh = state["shards"][sid]
            if sh["state"] != LEASED:
                continue
            expired = (sh["lease_expires"] is not None
                       and now > float(sh["lease_expires"]))
            if sh["owner"] in dead or expired:
                out.append(sid)
        return out


def make_dm_shards(numdms: int, shard_rows: int,
                   prefix: str = "dm") -> List[Tuple[str, int, int]]:
    """Split the DM axis [0, numdms) into ledger shard specs of up to
    `shard_rows` rows each."""
    if numdms <= 0:
        return []
    shard_rows = max(1, int(shard_rows))
    return [("%s%04d" % (prefix, i // shard_rows),
             i, min(i + shard_rows, numdms))
            for i in range(0, numdms, shard_rows)]
