"""Drift-scan preparation: carve a drifting observation into
overlapping per-pointing files.

The reference pairs its drift survey driver with prep scripts that
split a continuous drift scan into "beams"/pointings before the
per-pointing search flow runs (bin/GBT350_drift_prep.py:25-33,
bin/GUPPI_drift_prep.py): each pointing is ``orig_N`` samples,
successive pointings step by ``orig_N * overlap_factor`` (0.5 — 50%
overlap so no pulsar transit straddles a boundary unseen), and each
output file is renamed after the sky coordinates at its start
(GBT350_drift_prep.py:85-100: "GBT350drift_<MJDi>_<coords>.fil").

TPU-first differences from the reference scripts:

* format-agnostic input — anything ``open_raw`` can read (SIGPROC
  filterbank or PSRFITS, single file or a multi-file scan), not the
  Spigot-FITS-only path of the original; output is standard SIGPROC
  filterbank, the drift-survey interchange format.
* the per-pointing coordinates are computed, not read from
  per-subfile headers: in a drift scan the telescope is parked, so
  the touched RA advances at the sidereal rate while Dec is fixed.
  We advance the scan-start RA by ``360 deg * t_mid / 86164.0905 s``
  (one sidereal day) to the pointing'd midpoint.  The reference gets
  the same answer by trusting the backend's per-file headers
  (GBT350_drift_prep.py:88-91).
* one pass writes every pointing (or a selected one), so the
  pipeline app can run prep + per-pointing surveys as one command
  (``--recipe gbt350drift --driftprep``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from presto_tpu.io.atomic import atomic_open

SIDEREAL_DAY_S = 86164.0905

# GBT350 drift defaults (GBT350_drift_prep.py:25-27): ~141 s of the
# 81.92 us data per pointing, 50% overlap.
ORIG_N = 1728000
OVERLAP_FACTOR = 0.5


def _sigproc_to_deg_ra(src_raj: float) -> float:
    """SIGPROC hhmmss.s -> RA degrees."""
    sign = -1.0 if src_raj < 0 else 1.0
    v = abs(src_raj)
    hh = int(v // 10000)
    mm = int((v - hh * 10000) // 100)
    ss = v - hh * 10000 - mm * 100
    return sign * (hh + mm / 60.0 + ss / 3600.0) * 15.0


def _deg_ra_to_sigproc(deg: float) -> float:
    """RA degrees -> SIGPROC hhmmss.s."""
    hours = (deg % 360.0) / 15.0
    hh = int(hours)
    mm = int((hours - hh) * 60.0)
    ss = ((hours - hh) * 60.0 - mm) * 60.0
    if ss > 59.9999995:          # carry rounding
        ss = 0.0
        mm += 1
    if mm == 60:
        mm = 0
        hh = (hh + 1) % 24
    return hh * 10000 + mm * 100 + ss


def _coord_tag(src_raj: float, src_dej: float) -> str:
    """"hhmm[+-]ddmm" filename tag (GBT350_drift_prep.py:92-98)."""
    ra = abs(src_raj)
    ra_tag = "%02d%02d" % (int(ra // 10000), int((ra % 10000) // 100))
    de = abs(src_dej)
    sign = "-" if src_dej < 0 else "+"
    de_tag = "%s%02d%02d" % (sign, int(de // 10000),
                             int((de % 10000) // 100))
    return ra_tag + de_tag


@dataclass
class DriftPointing:
    num: int
    start_sample: int
    nsamp: int
    src_raj: float       # SIGPROC hhmmss.s at the pointing midpoint
    src_dej: float
    tstart: float        # MJD of first sample
    path: str = ""


def plan_pointings(total_samples: int, tsamp: float, tstart: float,
                   src_raj: float, src_dej: float,
                   orig_N: int = ORIG_N,
                   overlap_factor: float = OVERLAP_FACTOR,
                   ) -> List[DriftPointing]:
    """Pointing layout for a drift scan: starts step by
    ``orig_N * overlap_factor``; NMAX = total/overlap_samples - 1
    (GBT350_drift_prep.py:44-46).  Short scans yield one pointing."""
    overlap_samples = max(1, int(orig_N * overlap_factor))
    n = max(1, total_samples // overlap_samples - 1)
    out = []
    for num in range(n):
        start = num * overlap_samples
        nsamp = min(orig_N, total_samples - start)
        if nsamp <= 0:
            break
        t_mid_s = (start + 0.5 * nsamp) * tsamp
        ra_deg = (_sigproc_to_deg_ra(src_raj)
                  + 360.0 * t_mid_s / SIDEREAL_DAY_S)
        out.append(DriftPointing(
            num=num, start_sample=start, nsamp=nsamp,
            src_raj=_deg_ra_to_sigproc(ra_deg), src_dej=src_dej,
            tstart=tstart + start * tsamp / 86400.0))
    return out


def split_drift_scan(rawfiles: Sequence[str], outdir: str = ".",
                     orig_N: int = ORIG_N,
                     overlap_factor: float = OVERLAP_FACTOR,
                     pointing: Optional[int] = None,
                     prefix: str = "drift",
                     max_block: int = 1 << 22) -> List[str]:
    """Split a raw drift scan into per-pointing SIGPROC files.

    Returns the written paths, sorted by pointing number.  With
    ``pointing`` set only that one pointing is cut (the reference
    scripts' per-NUM mode for cluster fan-out,
    GBT350_drift_prep.py:44-50).  Existing outputs are kept (the
    artifact-per-stage checkpoint contract).
    """
    from presto_tpu.apps.common import open_raw
    from presto_tpu.io.sigproc import FilterbankHeader, \
        write_filterbank_header, pack_bits

    os.makedirs(outdir, exist_ok=True)
    fb = open_raw(list(rawfiles))
    try:
        hdr = fb.header
        total = int(fb.nspectra)
        plan = plan_pointings(
            total, hdr.tsamp, hdr.tstart, hdr.src_raj, hdr.src_dej,
            orig_N=orig_N, overlap_factor=overlap_factor)
        todo = [p for p in plan
                if pointing is None or p.num == pointing]
        if pointing is not None and not todo:
            raise ValueError(
                "pointing %d > NMAX (%d)" % (pointing, len(plan) - 1))
        written = []
        for p in todo:
            tag = _coord_tag(p.src_raj, p.src_dej)
            name = "%s_%d_%s_p%04d.fil" % (prefix, int(p.tstart),
                                           tag, p.num)
            path = os.path.join(outdir, name)
            p.path = path
            written.append(path)
            if os.path.exists(path):
                # reuse only when the existing cut matches THIS plan's
                # geometry (a rerun with different orig_N/overlap
                # collides on the name but must not keep stale cuts)
                from presto_tpu.io.sigproc import FilterbankFile
                try:
                    with FilterbankFile(path) as old:
                        # same sample count AND same start time: a
                        # rerun with a different overlap_factor keeps
                        # nsamp but shifts start_sample — names can
                        # still collide at tag resolution.  Band
                        # geometry and sample format must also match:
                        # a rerun against a different input file (or
                        # requantization) keeps nsamp/tstart but must
                        # not keep the stale cut (ADVICE r4).
                        oh = old.header
                        reuse = (int(old.nspectra) == p.nsamp
                                 and abs(oh.tstart - p.tstart)
                                 < 0.5 * hdr.tsamp / 86400.0
                                 and oh.nchans == hdr.nchans
                                 and oh.nbits == (
                                     8 if getattr(hdr, "nbits", 8)
                                     not in (8, 16, 32) else hdr.nbits)
                                 and abs(oh.fch1 - hdr.fch1) < 1e-9
                                 and abs(oh.foff - hdr.foff) < 1e-12
                                 and abs(oh.tsamp - hdr.tsamp) < 1e-12)
                except Exception:
                    reuse = False     # unreadable: rewrite it
                if reuse:
                    continue
                # no unlink: atomic_open overwrites atomically, so a
                # crash mid-rewrite leaves the old artifact
            out_hdr = FilterbankHeader(
                source_name="%s_%s" % (prefix, tag),
                machine_id=getattr(hdr, "machine_id", 10),
                telescope_id=getattr(hdr, "telescope_id", 0),
                fch1=hdr.fch1, foff=hdr.foff, nchans=hdr.nchans,
                nbits=8 if getattr(hdr, "nbits", 8) not in (8, 16, 32)
                else hdr.nbits,
                tstart=p.tstart, tsamp=hdr.tsamp,
                src_raj=p.src_raj, src_dej=p.src_dej)
            with atomic_open(path, "wb") as f:
                write_filterbank_header(out_hdr, f)
                # stream in bounded blocks: a full pointing at GBT350
                # scale is ~3.4 GB of float work otherwise
                for s0 in range(p.start_sample,
                                p.start_sample + p.nsamp, max_block):
                    cnt = min(max_block,
                              p.start_sample + p.nsamp - s0)
                    block = fb.read_spectra(s0, cnt)
                    if out_hdr.foff < 0:
                        block = block[:, ::-1]
                    if out_hdr.nbits == 32:
                        # 32-bit SIGPROC is float32: write samples
                        # verbatim (rounding/clipping would zero every
                        # negative sample of bandpass-subtracted data)
                        arr = block
                    else:
                        arr = np.clip(np.rint(block), 0,
                                      (1 << out_hdr.nbits) - 1)
                    f.write(pack_bits(
                        np.ascontiguousarray(arr).ravel(),
                        out_hdr.nbits).tobytes())
        return written
    finally:
        fb.close()
