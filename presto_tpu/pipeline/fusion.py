"""Device-resident stage fusion for the survey pipeline.

BENCH_r05 put the accel kernel at 2.93e9 cells/s device-resident but
only 1.10e9 cells/s inclusive: the gap is host transfers, per-stage
``.dat``/``.fft`` disk round-trips, and warmup — not compute.  The
staged survey (pipeline/survey.py) materializes every stage boundary
to disk: prepsubband downloads the DM fan-out and writes ``.dat``
files, the FFT stage reads them back and re-uploads, and the
single-pulse stage reads them from disk a third time.  This module
gives stages an IN-MEMORY seam instead: dedispersed series flow
HBM -> (zap) -> FFT -> accel/single-pulse search without touching
disk, and the artifact journal becomes an optional *durability tier*
rather than the data path (AstroAccelerate's FDAS gets its real-time
claim from exactly this shape: a device-resident dedisp->FFT->search
chain with ingest overlapped against compute).

Three pieces, each usable on its own:

``StageSeam``
    The hand-off object: a producer stage (prepsubband) deposits
    device arrays + per-trial metadata; consumer stages (realfft,
    accelsearch, single_pulse_search) read them without a disk
    round-trip.  ``spill()``/``ensure_dat()`` write the would-be
    artifacts (atomic + journaled) when durability — or a downstream
    consumer like prepfold — demands them; spilled bytes are counted
    on ``survey_fused_bytes_spilled_total`` and every hand-off/spill
    opens a ``pipeline:seam`` span.

``InflightWindow``
    Bounded cross-stage async dispatch: jax dispatches are async, so
    queueing stage N+1's work before collecting stage N's overlaps
    them — but an unbounded queue pins every intermediate buffer in
    HBM.  The window admits new in-flight values and forces the oldest
    once ``depth`` are pending (the jerk ladder's 2-deep pattern from
    search/accel.py, generalized).

``DoubleBufferedIngest``
    Host-side ingest overlap: a worker thread decodes/preprocesses
    block k+1 while the caller feeds block k to the device,
    generalizing the csrc/native_io.cpp feeder's raw-read prefetch to
    the whole decode->mask->clip->transpose stage.

The seam crosses the survey's app-CLI boundary (argv cannot carry
objects) the same way the elastic layer's injector does: the survey
installs a process-level seam with :func:`set_process_seam`, and
apps/prepsubband.py picks it up when its execution path is
seam-compatible (single-process, non--sub; sharded mesh and
barycentred runs included).  On the DM-sharded mesh path the deposit
is a :class:`ShardedSeamBlock`: one global jax.Array whose DM axis is
sharded over the mesh, each device holding the sub-range it
dedispersed (parallel/sharded.ShardedDedispPlan) — the downstream
sharded rFFT, in-memory zap, accel and single-pulse searches consume
the shards in place, and host download happens only at candidate
collection and durable spill (``gather_shards``).

Byte-identity invariant: fusion only changes WHERE bytes live between
stages, never their values.  The seam's device series are bit-equal
to the staged path's ``.dat`` bytes (the pad tail is computed on host
with the exact NumPy semantics of pad_to_good_N and uploaded), so any
artifact the fused path spills — and every always-written final
artifact (ACCEL/.cand/cands_sifted/.singlepulse) — is byte-identical
to a staged run's.  tests/test_fusion.py and the chaos matrix pin
this.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

import numpy as np

#: defaults for the fused pipeline's two depth knobs; the
#: ``pipeline_inflight_depth`` tune family (tune/space.py) overrides
#: them per device fingerprint.  Depths only change dispatch/ingest
#: overlap, never output bytes.
DEFAULT_WINDOW_DEPTH = 2     # cross-stage in-flight dispatches
DEFAULT_INGEST_DEPTH = 2     # host blocks decoded ahead of the device


def resolve_depths(inflight_depth: Optional[int] = None,
                   obs=None) -> Dict[str, int]:
    """The fused pipeline's depth knobs: an explicit caller value wins
    for the windows; otherwise the tuning DB's
    ``pipeline_inflight_depth`` (and, for the DM-sharded seam path,
    ``sharded_inflight_depth``) entries when tuning is active
    (presto_tpu/tune), else the defaults.  ``shard_window`` paces the
    sharded fused chain — its sweet spot differs from the
    single-device window because each in-flight chunk pins HBM on
    EVERY mesh device — and falls back to ``window`` when the sharded
    family has no measurement.  Clamped to [1, 8] — a depth only
    changes overlap, so any clamp is safe."""
    window, ingest = DEFAULT_WINDOW_DEPTH, DEFAULT_INGEST_DEPTH
    shard_window = None
    from presto_tpu import tune
    if tune.enabled():
        cfg = tune.best("pipeline_inflight_depth", tune.GLOBAL_KEY,
                        obs=obs)
        if cfg:
            try:
                window = int(cfg.get("window", window))
                ingest = int(cfg.get("ingest_depth", ingest))
            except (TypeError, ValueError):
                pass
        scfg = tune.best("sharded_inflight_depth", tune.GLOBAL_KEY,
                         obs=obs)
        if scfg:
            try:
                shard_window = int(scfg.get("window"))
            except (TypeError, ValueError):
                pass
    if inflight_depth is not None:
        window = int(inflight_depth)
        shard_window = int(inflight_depth)
    if shard_window is None:
        shard_window = window
    return {"window": max(1, min(int(window), 8)),
            "ingest_depth": max(1, min(int(ingest), 8)),
            "shard_window": max(1, min(int(shard_window), 8))}


def inf_float(x, digits: int = 15) -> float:
    """The value a staged consumer reads back from a ``.inf`` sidecar:
    the ``{:.Ng}`` text roundtrip (io/infodata.py writes dt with 15
    significant digits, dm with 12).  Seam consumers must use THIS —
    not the full-precision float — wherever the staged path derives a
    number from the sidecar, or fused and staged artifacts could
    differ in the last ulp."""
    return float(("%%.%dg" % int(digits)) % float(x))


# ----------------------------------------------------------------------
# InflightWindow
# ----------------------------------------------------------------------

class InflightWindow:
    """Keep at most ``depth`` async device computations in flight.

    ``admit(x)`` registers a freshly-dispatched value (any pytree of
    jax arrays); when more than ``depth`` are pending the OLDEST is
    forced (block_until_ready) and released — so stage N+1's dispatch
    overlaps stage N's execution while HBM holds a bounded number of
    intermediates.  ``drain()`` forces everything left."""

    def __init__(self, depth: int = DEFAULT_WINDOW_DEPTH):
        self.depth = max(1, int(depth))
        self._pending: List[object] = []

    def admit(self, x) -> None:
        self._pending.append(x)
        while len(self._pending) > self.depth:
            self._force(self._pending.pop(0))

    def drain(self) -> None:
        while self._pending:
            self._force(self._pending.pop(0))

    @staticmethod
    def _force(x) -> None:
        try:
            import jax
            jax.block_until_ready(x)
        except Exception:
            pass     # host values (or no backend): nothing to await


# ----------------------------------------------------------------------
# DoubleBufferedIngest
# ----------------------------------------------------------------------

class _IngestStop(Exception):
    pass


class DoubleBufferedIngest:
    """Iterate ``source`` on a worker thread, ``depth`` items ahead.

    The producer runs the expensive host-side block work (read,
    decode, mask/clip, transpose) while the consumer keeps the device
    busy with the previous block — the (data, lastdata) double-buffer
    of the reference's streaming loop lifted to the whole ingest
    stage.  Items are delivered strictly in order; a producer
    exception is re-raised at the consumer's next pull, and close()
    always joins the thread."""

    def __init__(self, source: Iterator, depth: int = DEFAULT_INGEST_DEPTH):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._done = object()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, args=(source,), daemon=True,
            name="presto-ingest")
        self._thread.start()

    def _run(self, source) -> None:
        try:
            for item in source:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:           # relay to the consumer
            self._exc = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._done, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        try:                                 # unblock a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ----------------------------------------------------------------------
# StageSeam
# ----------------------------------------------------------------------

@dataclass
class SeamBlock:
    """One prepsubband method's DM fan-out held at the seam: the
    device-resident padded series (the FFT stage's input block), the
    bit-identical host copy (artifact/spill/fold source), and the
    per-trial metadata a consumer stage would otherwise re-read from
    ``.inf`` sidecars."""
    names: List[str]            # per-trial base paths (no extension)
    infos: List[object]         # per-trial InfoData
    dms: List[float]
    series_dev: object          # [ntrials, numout] float32 jax array
    series_host: np.ndarray     # same bytes, host side
    valid: int                  # data samples before the pad
    numout: int                 # padded length
    dt: float                   # post-downsample sample time
    T: float = 0.0              # numout * dt (searcher geometry)

    def __post_init__(self):
        if not self.T:
            self.T = self.numout * self.dt


@dataclass
class ShardedSeamBlock(SeamBlock):
    """A SeamBlock whose ``series_dev`` is ONE global jax.Array with
    the DM axis sharded over ``mesh`` (parallel/mesh dm_sharding):
    each device holds exactly the DM sub-range it dedispersed
    (parallel/sharded.ShardedDedispPlan), and downstream consumers —
    the DM-sharded batched rFFT, in-memory zapbirds, search_many and
    single-pulse — operate on the shards IN PLACE.  The host copy is
    assembled per shard (``gather_shards``: parallel per-device D2H,
    no cross-device gather) and exists for the same reason the
    unsharded block's does: the pad tail must be computed with
    pad_to_good_N's exact NumPy semantics, and spills/folds/candidate
    refinement read host bytes.  Placement-aware spill = the durable
    tier writes each DM trial's ``.dat`` from that assembled copy
    without ever staging the fan-out through a single device."""
    mesh: object = None


class StageSeam:
    """In-memory seam between survey stages (see module docstring).

    ``durable`` selects the durability tier: True spills every
    deposited block's artifacts immediately (the staged contract with
    the disk round-trip removed from the CONSUMER side only); False —
    the presto-serve/bench tier — writes nothing until a consumer
    calls ``ensure_dat`` (prepfold) or ``spill`` explicitly."""

    def __init__(self, workdir: str, durable: bool = False,
                 manifest=None, obs=None,
                 inflight_depth: Optional[int] = None):
        self.workdir = os.path.abspath(workdir)
        self.durable = bool(durable)
        self.manifest = manifest
        self.obs = obs
        self.blocks: List[SeamBlock] = []
        self.depths = resolve_depths(inflight_depth, obs=obs)
        self._by_dat: Dict[str, tuple] = {}   # .dat path -> (block, row)
        self._spilled: set = set()

    # -- producer side -------------------------------------------------

    def add_block(self, block: SeamBlock) -> None:
        """Deposit one method's fan-out at the seam (producer side).
        The ``.inf`` sidecars are written on EVERY tier — they are
        per-trial metadata the final-artifact consumers (sifting,
        prepfold) read from disk, not the bulk data path."""
        from presto_tpu.io.infodata import write_inf
        sp = self._span("handoff", trials=len(block.names),
                        numout=block.numout,
                        sharded=is_sharded(block))
        self.blocks.append(block)
        infs = []
        for row, name in enumerate(block.names):
            self._by_dat[os.path.abspath(name + ".dat")] = (block, row)
            write_inf(block.infos[row], name + ".inf")
            infs.append(name + ".inf")
        if self.manifest is not None:
            self.manifest.record_many(
                [p for p in infs if os.path.exists(p)], "prepsubband")
        if self.obs is not None and self.obs.enabled:
            self.obs.metrics.counter(
                "survey_fused_trials_total",
                "DM trials handed across the in-memory stage seam"
            ).inc(len(block.names))
            if is_sharded(block):
                self.obs.metrics.counter(
                    "survey_fused_shard_trials_total",
                    "DM trials handed across the seam as device "
                    "shards (one DM sub-range per mesh device)"
                ).inc(len(block.names))
        if self.durable:
            self.spill(block)
        if sp is not None:
            sp.finish()

    # -- consumer side -------------------------------------------------

    def __len__(self) -> int:
        return sum(len(b.names) for b in self.blocks)

    def dat_paths(self) -> List[str]:
        return sorted(self._by_dat)

    def groups(self) -> Dict[int, List[SeamBlock]]:
        """Blocks grouped by padded length (the FFT/search batching
        axis, mirroring the staged path's _length_groups)."""
        by_len: Dict[int, List[SeamBlock]] = {}
        for b in self.blocks:
            by_len.setdefault(b.numout, []).append(b)
        return by_len

    # -- durability tier -----------------------------------------------

    def spill(self, block: Optional[SeamBlock] = None,
              record_stage: str = "prepsubband") -> int:
        """Write the ``.dat``+``.inf`` artifacts for one block (or
        all), atomic + journaled — the staged path's durable outputs,
        produced from the seam's host copy.  Returns bytes written."""
        from presto_tpu.io.datfft import write_dat
        blocks = [block] if block is not None else list(self.blocks)
        total = 0
        for b in blocks:
            sp = self._span("spill", trials=len(b.names),
                            numout=b.numout, sharded=is_sharded(b))
            written = []
            for row, name in enumerate(b.names):
                dat = name + ".dat"
                if os.path.abspath(dat) in self._spilled:
                    continue
                write_dat(dat, b.series_host[row], b.infos[row])
                self._spilled.add(os.path.abspath(dat))
                written += [dat, name + ".inf"]
                total += b.series_host[row].nbytes
            if written and self.manifest is not None:
                self.manifest.record_many(
                    [p for p in written if os.path.exists(p)],
                    record_stage)
            if sp is not None:
                sp.finish()
        self._count_spill(total)
        return total

    def ensure_dat(self, datpath: str) -> bool:
        """Spill ONE trial's ``.dat``+``.inf`` on demand (prepfold
        reads its candidate's series from disk).  Returns True when
        the path is now on disk (or was never seam-held)."""
        key = os.path.abspath(datpath)
        ent = self._by_dat.get(key)
        if ent is None:
            return os.path.exists(datpath)
        if key in self._spilled or os.path.exists(datpath):
            return True
        from presto_tpu.io.datfft import write_dat
        block, row = ent
        sp = self._span("spill", trials=1, numout=block.numout,
                        on_demand=True, sharded=is_sharded(block))
        write_dat(datpath, block.series_host[row], block.infos[row])
        self._spilled.add(key)
        if self.manifest is not None:
            self.manifest.record_many(
                [p for p in (datpath, block.names[row] + ".inf")
                 if os.path.exists(p)], "prepsubband")
        self._count_spill(block.series_host[row].nbytes)
        if sp is not None:
            sp.finish()
        return True

    def release(self, block: SeamBlock) -> None:
        """Drop the seam's reference to a block's DEVICE array (the
        host copy stays for spills) — lets a consumer donate the
        buffer to its own computation."""
        block.series_dev = None

    # -- internals -----------------------------------------------------

    def _span(self, op: str, sharded: bool = False, **attrs):
        if self.obs is None or not self.obs.enabled:
            return None
        if sharded:
            return self.obs.span("pipeline:shard-seam", op=op, **attrs)
        return self.obs.span("pipeline:seam", op=op, **attrs)

    def _count_spill(self, nbytes: int) -> None:
        if nbytes and self.obs is not None and self.obs.enabled:
            self.obs.metrics.counter(
                "survey_fused_bytes_spilled_total",
                "Seam-held artifact bytes spilled to the durable tier"
            ).inc(int(nbytes))


# ----------------------------------------------------------------------
# fused device helpers
# ----------------------------------------------------------------------

def is_sharded(block) -> bool:
    """Is this seam block's device series mesh-sharded on the DM axis?"""
    return getattr(block, "mesh", None) is not None


def gather_shards(arr, obs=None) -> np.ndarray:
    """Placement-aware D2H of a DM-sharded device array: each device's
    shard downloads independently into its row range of the host
    buffer (parallel per-device transfers, never a cross-device gather
    through one chip).  This is the sharded seam's ONLY bulk download
    — it feeds the pad computation, the durable spill, and candidate
    refinement; counted on survey_fused_shard_gather_bytes_total."""
    out = np.empty(arr.shape, dtype=arr.dtype)
    total = 0
    for sh in arr.addressable_shards:
        data = np.asarray(sh.data)
        out[sh.index] = data
        total += data.nbytes
    if obs is not None and getattr(obs, "enabled", False):
        obs.metrics.counter(
            "survey_fused_shard_gather_bytes_total",
            "Bytes downloaded per-shard from the DM-sharded seam "
            "(pad/spill/candidate collection)").inc(int(total))
        from presto_tpu.obs import jaxtel
        jaxtel.note_get(obs, total)
    return out


_fft_fns: dict = {}


def fused_rfft_batch(series_dev, donate: bool = False, obs=None,
                     mesh=None):
    """Batched packed real FFT of the seam's series block, optionally
    DONATING the input buffer to XLA (the dedisp output block becomes
    the FFT's workspace — input [n, N] float32 and output [n, N/2, 2]
    float32 are the same size, so donation makes the seam crossing
    allocation-neutral).  Identical floats either way; donation only
    changes buffer lifetime.

    With ``mesh`` the batch axis is the DM-sharded axis and the FFT
    runs shard_map'd: each device transforms ONLY its own rows and
    the spectra stay on the device that dedispersed the series.  The
    shard_map is load-bearing, not style — a plain jit (even with
    out_shardings pinned) lets GSPMD compute the batched FFT
    replicated and slice afterwards, which both re-gathers the
    fan-out and multiplies the FLOPs by the device count (measured 7x
    slower on the 8-device CPU mesh).  Per-row FFTs are independent,
    so the per-shard program computes identical floats."""
    import jax
    from presto_tpu.ops import fftpack
    key = (bool(donate), mesh)
    fn = _fft_fns.get(key)
    if fn is None:
        kw = {"donate_argnums": 0} if donate else {}
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            from presto_tpu.parallel.sharded import _shard_map
            axis = mesh.axis_names[0]
            fn = jax.jit(_shard_map(
                jax.vmap(fftpack.realfft_packed_pairs), mesh=mesh,
                in_specs=P(axis, None),
                out_specs=P(axis, None, None)), **kw)
        else:
            fn = jax.jit(jax.vmap(fftpack.realfft_packed_pairs), **kw)
        _fft_fns[key] = fn
    from presto_tpu.obs import costmodel, jaxtel
    costmodel.probe(obs, "rfft_batch", fn, series_dev)
    jaxtel.note_dispatch(obs, "rfft_batch")
    if donate:
        jaxtel.note_donation(obs, int(np.prod(series_dev.shape)) * 4)
    return fn(series_dev)


# ----------------------------------------------------------------------
# process-level seam hand-off (the argv boundary, like
# parallel/elastic.set_process_injector)
# ----------------------------------------------------------------------

_process_seam: Optional[StageSeam] = None


def set_process_seam(seam: Optional[StageSeam]) -> None:
    """Install (or clear) the seam the next seam-aware app run in this
    process should deposit into.  The survey driver brackets its
    prepsubband calls with this; app CLIs launched any other way see
    None and keep the staged contract."""
    global _process_seam
    _process_seam = seam


def current_process_seam() -> Optional[StageSeam]:
    return _process_seam
