"""Pipeline orchestration: DDplan, candidate sifting, survey drivers.

The analog of the reference's bin/ scripts layer (SURVEY.md L7).
"""
