"""Per-survey artifact journal (the verify half of crash-safe resume).

The survey's checkpoint contract used to be "a stage is skipped when
its outputs already exist" — which trusts whatever bytes happen to be
on disk, including a file truncated by a kill or rotted by a bad disk.
With io/atomic.py a *partial* artifact can no longer land under its
final name, and this journal closes the remaining gap: after each
stage completes, run_survey records every output's size + CRC-32 here;
on resume an artifact is trusted only when it exists AND matches its
journal entry.  Anything missing, unjournaled (e.g. written by a run
killed between the rename and the journal update, or by a pre-journal
version of the code), truncated, or checksum-stale is deleted and its
stage redone — safe because every stage is deterministic.

The journal itself (`manifest.json`) is written atomically, so it is
always a consistent snapshot of some prefix of the survey's progress.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List

from presto_tpu.io.atomic import atomic_write_text, file_checksum

MANIFEST_NAME = "manifest.json"

#: verify() statuses that mean "redo the stage that makes this file"
STALE = ("missing", "unjournaled", "size-mismatch", "checksum-mismatch")


class SurveyManifest:
    """size+checksum journal for one survey working directory."""

    def __init__(self, workdir: str):
        self.workdir = os.path.abspath(workdir)
        self.path = os.path.join(self.workdir, MANIFEST_NAME)
        # relpath -> {"size": int, "checksum": str, "stage": str}
        self.entries: Dict[str, dict] = {}

    # -- persistence --------------------------------------------------
    @classmethod
    def load(cls, workdir: str) -> "SurveyManifest":
        m = cls(workdir)
        try:
            with open(m.path) as f:
                obj = json.load(f)
            entries = obj.get("artifacts", {})
            if isinstance(entries, dict):
                m.entries = {str(k): dict(v)
                             for k, v in entries.items()}
        except (OSError, ValueError):
            # missing or corrupt journal: start empty — every artifact
            # then reads as unjournaled and its stage is redone, the
            # safe direction.
            m.entries = {}
        return m

    def save(self) -> None:
        atomic_write_text(self.path, json.dumps(
            {"version": 1, "artifacts": self.entries},
            indent=1, sort_keys=True) + "\n")

    # -- recording ----------------------------------------------------
    def _key(self, path: str) -> str:
        return os.path.relpath(os.path.abspath(path), self.workdir)

    def record(self, path: str, stage: str = "",
               save: bool = False) -> None:
        self.entries[self._key(path)] = {
            "size": os.path.getsize(path),
            "checksum": file_checksum(path),
            "stage": stage,
        }
        if save:
            self.save()

    def record_many(self, paths: Iterable[str], stage: str = "",
                    save: bool = True) -> None:
        for p in paths:
            self.record(p, stage=stage)
        if save:
            self.save()

    def forget(self, path: str) -> None:
        self.entries.pop(self._key(path), None)

    def stage_of(self, path: str) -> str:
        """Stage tag recorded for `path` ('' when unjournaled) — lets
        in-place mutators (zapbirds) distinguish done from pending."""
        entry = self.entries.get(self._key(path))
        return str(entry.get("stage", "")) if entry else ""

    # -- verification -------------------------------------------------
    def verify(self, path: str) -> str:
        """'ok' | 'missing' | 'unjournaled' | 'size-mismatch' |
        'checksum-mismatch' for one artifact."""
        if not os.path.exists(path):
            return "missing"
        entry = self.entries.get(self._key(path))
        if entry is None:
            return "unjournaled"
        if os.path.getsize(path) != entry.get("size"):
            return "size-mismatch"
        if file_checksum(path) != entry.get("checksum"):
            return "checksum-mismatch"
        return "ok"

    def valid(self, path: str) -> bool:
        return self.verify(path) == "ok"

    def invalidate_stale(self, paths: Iterable[str],
                         remove: bool = True) -> List[str]:
        """Return the stale subset of `paths`; with remove=True the
        on-disk stragglers are deleted (so globs can't resurrect them)
        and their journal entries dropped."""
        stale = []
        for p in paths:
            status = self.verify(p)
            if status == "ok":
                continue
            stale.append(p)
            if remove and os.path.exists(p):
                try:
                    os.remove(p)
                except OSError:
                    pass
            self.forget(p)
        return stale
