"""DDplan: optimal dedispersion planning.

Reference: bin/DDplan.py — choose (dDM, downsamp, dsubDM, #DMs, #calls)
per DM range so the total smearing (quadrature sum of sample time,
per-channel DM smearing, subband step smearing, and DM step smearing
across the band) stays near the floor set by the data, stepping to
coarser dDM/downsamp as channel smearing grows with DM.

Smearing model (DDplan.py:141-190):
  dm_smear       t = 1000 * |DM - cDM| * BW / (0.0001205 f^3)   [ms]
  BW_smear       dm_smear at the worst-case step error dDM/2 over BW
  subband_smear  dm_smear at dsubDM/2 over BW/numsub
Plan construction (dm_steps, DDplan.py:205-295): pick downsamp so
eff_dt tracks the channel smearing, pick dDM from an allowed ladder so
BW smearing ~ eff_dt, extend each method until channel smearing
dominates by smearfact=2, then coarsen.

Pure planning math — host float64, no device work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np

ALLOW_DDMS = (0.01, 0.02, 0.03, 0.05, 0.1, 0.2, 0.3, 0.5, 1.0,
              2.0, 3.0, 5.0, 10.0, 20.0, 30.0, 50.0, 100.0, 200.0, 300.0)
ALLOW_DOWNSAMPS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
FF = 1.2          # time-scale equality fudge factor (DDplan.py:218)


def dm_smear(dm, bw_mhz, f_ctr_mhz, cdm=0.0):
    """Smearing (ms) from DM over bw centered at f_ctr (DDplan.py:146)."""
    return 1000.0 * np.abs(dm - cdm) * bw_mhz / (0.0001205
                                                 * f_ctr_mhz ** 3)


def bw_smear(dm_step, bw_mhz, f_ctr_mhz):
    """Worst-case step-error smearing over the band (DDplan.py:153)."""
    return dm_smear(0.5 * dm_step, bw_mhz, f_ctr_mhz)


def guess_dm_step(dt, bw_mhz, f_ctr_mhz):
    """dDM that makes full-band smearing equal dt (DDplan.py:161)."""
    return dt * 0.0001205 * f_ctr_mhz ** 3 / (0.5 * bw_mhz)


def subband_smear(sub_dm_step, numsub, bw_mhz, f_ctr_mhz):
    """Step-error smearing within one subband (DDplan.py:169)."""
    if numsub == 0:
        return 0.0
    return dm_smear(0.5 * sub_dm_step, bw_mhz / numsub, f_ctr_mhz)


@dataclass
class Observation:
    dt: float            # s
    f_ctr: float         # MHz
    bw: float            # MHz
    numchan: int
    cdm: float = 0.0     # coherent (already-removed) DM

    @property
    def chanwidth(self) -> float:
        return self.bw / self.numchan


@dataclass
class DedispMethod:
    """One row of the DDplan table: a (dDM, downsamp) regime."""
    obs: Observation
    downsamp: int
    lodm: float
    ddm: float
    numsub: int = 0
    bw_smearing: float = 0.0
    dsub_dm: float = 0.0
    dms_per_prepsub: int = 0
    numprepsub: int = 0
    numdms: int = 0
    hidm: float = 0.0

    @property
    def dms(self) -> np.ndarray:
        return self.lodm + np.arange(self.numdms) * self.ddm

    def chan_smear(self, dm):
        dm = np.where(np.asarray(dm) - self.obs.cdm == 0.0,
                      self.obs.cdm + self.ddm / 2.0, dm)
        return dm_smear(dm, self.obs.chanwidth, self.obs.f_ctr,
                        self.obs.cdm)

    def total_smear(self, dm):
        """Quadrature total (DDplan.py:71-82)."""
        return np.sqrt((1000.0 * self.obs.dt) ** 2
                       + (1000.0 * self.obs.dt * self.downsamp) ** 2
                       + self.bw_smearing ** 2
                       + subband_smear(self.dsub_dm, self.numsub,
                                       self.obs.bw, self.obs.f_ctr) ** 2
                       + self.chan_smear(dm) ** 2)

    def dm_for_smearfact(self, smearfact: float) -> float:
        """DM where channel smearing = smearfact x everything else
        (DDplan.py:83-92)."""
        other = np.sqrt((1000.0 * self.obs.dt) ** 2
                        + (1000.0 * self.obs.dt * self.downsamp) ** 2
                        + self.bw_smearing ** 2
                        + subband_smear(self.dsub_dm, self.numsub,
                                        self.obs.bw,
                                        self.obs.f_ctr) ** 2)
        return smearfact * 0.001 * other / self.obs.chanwidth \
            * 0.0001205 * self.obs.f_ctr ** 3 + self.obs.cdm

    def __str__(self):
        if self.numsub:
            return ("%9.3f  %9.3f  %6.2f    %4d  %6.2f  %6d  %6d  %6d"
                    % (self.lodm, self.hidm, self.ddm, self.downsamp,
                       self.dsub_dm, self.numdms, self.dms_per_prepsub,
                       self.numprepsub))
        return "%9.3f  %9.3f  %6.2f    %4d  %6d" % (
            self.lodm, self.hidm, self.ddm, self.downsamp, self.numdms)


def make_method(obs: Observation, downsamp: int, lodm: float,
                hidm: float, ddm: float, numsub: int = 0,
                smearfact: float = 2.0) -> DedispMethod:
    """Build one regime: subband step sizing + crossover DM
    (dedisp_method.__init__, DDplan.py:22-61)."""
    m = DedispMethod(obs=obs, downsamp=downsamp, lodm=lodm, ddm=ddm,
                     numsub=numsub)
    m.bw_smearing = bw_smear(ddm, obs.bw, obs.f_ctr)
    if numsub:
        dms_per = 2
        while True:
            next_dsub = (dms_per + 2) * ddm
            next_ss = subband_smear(next_dsub, numsub, obs.bw, obs.f_ctr)
            # 0.8 fudge keeps subband smearing subdominant (DDplan.py:38)
            if next_ss > 0.8 * min(m.bw_smearing,
                                   1000.0 * obs.dt * downsamp):
                m.dsub_dm = dms_per * ddm
                m.dms_per_prepsub = dms_per
                break
            dms_per += 2
    else:
        m.dsub_dm = ddm
    # The crossover may fall below lodm when channel smearing already
    # dominates there — clamp so every regime covers at least one step
    # (otherwise numdms goes negative and the plan is empty).
    cross = min(max(m.dm_for_smearfact(smearfact), lodm + ddm), hidm)
    m.numdms = max(int(np.ceil((cross - lodm) / ddm)), 1)
    if numsub:
        m.numprepsub = int(np.ceil(m.numdms * ddm / m.dsub_dm))
        m.numdms = m.numprepsub * m.dms_per_prepsub
    m.hidm = lodm + m.numdms * ddm
    return m


@dataclass
class DDplan:
    obs: Observation
    lodm: float
    hidm: float
    methods: List[DedispMethod] = field(default_factory=list)

    @property
    def total_numdms(self) -> int:
        return sum(m.numdms for m in self.methods)

    @property
    def dms(self) -> np.ndarray:
        return np.concatenate([m.dms for m in self.methods]) \
            if self.methods else np.zeros(0)

    def work_fracts(self) -> np.ndarray:
        w = np.array([m.numdms / m.downsamp for m in self.methods],
                     dtype=np.float64)
        return w / w.sum()

    def __str__(self):
        sub = self.methods and self.methods[0].numsub
        if sub:
            hdr = ("  Low DM    High DM     dDM  DownSamp  dsubDM   "
                   "#DMs  DMs/call  calls")
        else:
            hdr = "  Low DM    High DM     dDM  DownSamp   #DMs"
        rows = [hdr] + [str(m) for m in self.methods]
        return "\n".join(rows) + "\n"


def plan_dedispersion(obs: Observation, lodm: float, hidm: float,
                      numsub: int = 0, ok_smearing: float = 0.0,
                      allow_ddms=ALLOW_DDMS,
                      allow_downsamps=ALLOW_DOWNSAMPS) -> DDplan:
    """Compute the DDplan (dm_steps, DDplan.py:205-295)."""
    dtms = 1000.0 * obs.dt
    min_chan_smearing = float(dm_smear(
        np.linspace(lodm, hidm, 10000), obs.chanwidth, obs.f_ctr,
        obs.cdm).min())
    ok_smearing = max(ok_smearing, min_chan_smearing,
                      bw_smear(allow_ddms[0], obs.bw, obs.f_ctr), dtms)

    i_ds = 0
    if FF * min_chan_smearing > dtms or ok_smearing > dtms:
        okval = ok_smearing if ok_smearing > FF * min_chan_smearing \
            else FF * min_chan_smearing
        while (i_ds + 1 < len(allow_downsamps)
               and dtms * allow_downsamps[i_ds + 1] < okval):
            i_ds += 1
    downsamp = allow_downsamps[i_ds]

    i_ddm = 0
    ddm_guess = guess_dm_step(obs.dt * downsamp, obs.bw, obs.f_ctr)
    while (i_ddm + 1 < len(allow_ddms)
           and allow_ddms[i_ddm + 1] < FF * ddm_guess):
        i_ddm += 1

    plan = DDplan(obs=obs, lodm=lodm, hidm=hidm)
    plan.methods.append(make_method(obs, downsamp, lodm, hidm,
                                    allow_ddms[i_ddm], numsub=numsub))
    while plan.methods[-1].hidm < hidm:
        i_ds = min(i_ds + 1, len(allow_downsamps) - 1)
        downsamp = allow_downsamps[i_ds]
        eff_dt = dtms * downsamp
        while (i_ddm + 1 < len(allow_ddms)
               and bw_smear(allow_ddms[i_ddm + 1], obs.bw,
                            obs.f_ctr) < FF * eff_dt):
            i_ddm += 1
        nxt = make_method(obs, downsamp, plan.methods[-1].hidm, hidm,
                          allow_ddms[i_ddm], numsub=numsub)
        if nxt.numdms <= 0:
            break
        plan.methods.append(nxt)
    return plan
