"""Fleet-wide metric aggregation + cross-process trace joining.

PR 3 made observability process-wide; the fleet (router + N replicas
+ the shared job ledger) made one process's view a fraction of the
story: `/metrics` answered per-replica, a DAG's spans landed in N
unrelated JSONL files, and nothing could answer "what is the fleet's
job p99?".  This module is the aggregation half of the fix:

  * **Snapshots** — each replica periodically publishes its full
    registry state (`MetricsRegistry.export_state`) as one atomic
    file `<fleet>/obs/<replica>.json` (io/atomic, tombstoned on
    graceful drain exactly like heartbeats), so aggregation is a
    lock-free read of small files — no replica RPC, no scrape race.
  * **Merging** — `merge_states` folds N exports into one fleet view:
    counters are summed, gauges become per-replica labeled series
    (a gauge is a point-in-time fact about ONE process), histograms
    are bucket-merged (element-wise bucket counts, summed count/sum,
    sample windows combined as a sorted multiset) so fleet-wide
    nearest-rank p50/p99 equal what a single shared registry would
    have reported.  The merge is associative and commutative over
    canonical states (tests/test_fleetobs.py pins both plus the
    single-registry equivalence under random shard splits).
  * **Traces** — `load_fleet_spans` joins the per-process
    `*.spans.jsonl` streams under `<fleet>/obs/`; spans carry
    trace/span/parent ids stamped through the ledger
    (`SpanContext.to_dict` on the admitted row), so grouping by
    trace id reconstructs one cross-process timeline per submission
    or DAG — exported as a single Perfetto file by
    `merged_chrome_trace` (tools/trace_merge.py is the CLI).
  * **Attribution** — `dag_critical_path` walks a DAG's ledger rows
    (submitted / leased_at / completed_at) to name the node chain
    that gated end-to-end latency and split each node's share into
    lease-wait vs execute time: exactly the per-bucket cost data the
    ROADMAP control-plane item (predictive admission, drain-time
    Retry-After) consumes — `serve/router.py` quotes Retry-After
    from the `job_e2e_seconds` aggregate here.

Everything reads through forgiving loaders: a torn, missing, or
stale-schema snapshot degrades to "not there", never to a failed
scrape.
"""

from __future__ import annotations

import copy
import glob
import json
import math
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from presto_tpu.io.atomic import atomic_write_text
from presto_tpu.obs.metrics import _fmt, _label_suffix

#: fleet telemetry directory (snapshots, span streams, dead-replica
#: flight-recorder dumps) inside a fleet working directory
OBS_DIRNAME = "obs"

SNAPSHOT_VERSION = 1

#: assumed publish cadence for snapshots that predate the
#: `interval_s` field (FleetConfig.snapshot_s default)
DEFAULT_SNAPSHOT_INTERVAL = 2.0

#: a live snapshot older than this many publish intervals is STALE:
#: its publisher missed several heartbeat-paced publishes, so its
#: counters under-report and its gauges describe the past —
#: `aggregate()` still merges it (that work happened) but flags it,
#: and /fleet/metrics + presto-report -fleet surface the warning
STALE_INTERVALS = 3.0


def obs_dir(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), OBS_DIRNAME)


def snapshot_path(fleetdir: str, replica: str) -> str:
    return os.path.join(obs_dir(fleetdir), "%s.json" % replica)


def span_stream_path(fleetdir: str, name: str) -> str:
    return os.path.join(obs_dir(fleetdir), "%s.spans.jsonl" % name)


def replica_dump_dir(fleetdir: str, replica: str) -> str:
    """Where a dying replica's flight-recorder dump lands (per
    replica, so the fleet report can attribute it after the ledger
    reaps the host)."""
    return os.path.join(obs_dir(fleetdir), replica)


# ----------------------------------------------------------------------
# snapshot publish / load
# ----------------------------------------------------------------------

def publish_snapshot(fleetdir: str, replica: str, obs,
                     tombstone: bool = False,
                     now: Optional[float] = None,
                     interval: Optional[float] = None) -> str:
    """Atomically publish one replica's full registry state.  A
    tombstone snapshot is the drain-time final word — the metric twin
    of the heartbeat tombstone: aggregation keeps the replica's
    counters (that work happened) but drops its gauges (stale
    point-in-time facts).  ``interval`` records the publisher's
    cadence so `aggregate()` can flag a snapshot that missed
    STALE_INTERVALS publishes as stale."""
    path = snapshot_path(fleetdir, replica)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "version": SNAPSHOT_VERSION,
        "replica": replica,
        "pid": os.getpid(),
        "ts": time.time() if now is None else now,
        "tombstone": bool(tombstone),
        "interval_s": float(interval if interval
                            else DEFAULT_SNAPSHOT_INTERVAL),
        "service": getattr(getattr(obs, "cfg", None), "service",
                           "presto_tpu"),
        "metrics": obs.metrics.export_state(),
    }
    atomic_write_text(path, json.dumps(payload, sort_keys=True) + "\n")
    return path


def load_snapshots(fleetdir: str) -> Dict[str, dict]:
    """{replica: snapshot payload} for every readable snapshot in the
    fleet obs dir (unparseable or wrong-schema files are skipped)."""
    out: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(obs_dir(fleetdir),
                                              "*.json"))):
        if path.endswith(".spans.jsonl"):
            continue
        try:
            with open(path) as f:
                snap = json.load(f)
            if (not isinstance(snap, dict)
                    or int(snap.get("version", -1))
                    != SNAPSHOT_VERSION
                    or "metrics" not in snap):
                continue
        except (OSError, ValueError):
            continue
        name = str(snap.get("replica")
                   or os.path.splitext(os.path.basename(path))[0])
        out[name] = snap
    return out


# ----------------------------------------------------------------------
# merging
# ----------------------------------------------------------------------

def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def canonicalize(replica: str, state: dict) -> dict:
    """One export_state -> the canonical merged form: gauges gain a
    ``replica`` label, histogram samples become a sorted multiset,
    series are keyed by their full label set.  merge() operates only
    on canonical states, which is what makes it associative."""
    out: Dict[str, dict] = {}
    for name, fam in (state.get("families") or {}).items():
        kind = str(fam.get("kind", "untyped"))
        labelnames = [str(x) for x in fam.get("labelnames") or []]
        ent = {"kind": kind, "help": str(fam.get("help", "")),
               "labelnames": list(labelnames), "series": {}}
        if kind == "gauge" and "replica" not in ent["labelnames"]:
            ent["labelnames"].append("replica")
        if kind == "histogram":
            ent["buckets"] = list(fam.get("buckets") or [])
        for s in fam.get("series") or []:
            labels = dict(s.get("labels") or {})
            if kind == "gauge":
                labels["replica"] = replica
            key = _label_key(labels)
            if kind == "histogram":
                ent["series"][key] = {
                    "labels": labels,
                    "count": int(s.get("count", 0)),
                    "sum": float(s.get("sum", 0.0)),
                    "bucket_counts": (list(s["bucket_counts"])
                                      if s.get("bucket_counts")
                                      is not None else None),
                    "samples": sorted(float(x) for x in
                                      s.get("samples") or []),
                }
            else:
                ent["series"][key] = {"labels": labels,
                                      "value": float(
                                          s.get("value", 0.0))}
        out[name] = ent
    return out


def merge(a: dict, b: dict) -> dict:
    """Merge two canonical states (commutative, associative).
    Counters/histogram totals sum; gauge series are disjoint by
    construction (per-replica labels) and collide to max; histograms
    with mismatched bucket layouts keep count/sum/samples but drop
    the unmergeable bucket counts (percentiles still work — they
    come from the merged sample windows)."""
    out: Dict[str, dict] = {}
    for name in sorted(set(a) | set(b)):
        fa, fb = a.get(name), b.get(name)
        if fa is None or fb is None:
            out[name] = copy.deepcopy(fa if fb is None else fb)
            continue
        if fa["kind"] != fb["kind"]:
            out[name] = copy.deepcopy(fa)
            continue
        ent = {"kind": fa["kind"], "help": fa["help"] or fb["help"],
               "labelnames": list(fa["labelnames"]), "series": {}}
        same_buckets = True
        if fa["kind"] == "histogram":
            same_buckets = (fa.get("buckets") == fb.get("buckets"))
            ent["buckets"] = list(fa.get("buckets") or [])
        for key in sorted(set(fa["series"]) | set(fb["series"])):
            sa, sb = fa["series"].get(key), fb["series"].get(key)
            if sa is None or sb is None:
                merged = copy.deepcopy(sa if sb is None else sb)
            elif fa["kind"] == "histogram":
                bc = None
                if (same_buckets
                        and sa.get("bucket_counts") is not None
                        and sb.get("bucket_counts") is not None):
                    bc = [x + y for x, y in
                          zip(sa["bucket_counts"],
                              sb["bucket_counts"])]
                merged = {
                    "labels": dict(sa["labels"]),
                    "count": sa["count"] + sb["count"],
                    "sum": sa["sum"] + sb["sum"],
                    "bucket_counts": bc,
                    "samples": sorted(sa["samples"] + sb["samples"]),
                }
            elif fa["kind"] == "counter":
                merged = {"labels": dict(sa["labels"]),
                          "value": sa["value"] + sb["value"]}
            else:                       # gauge collision: max wins
                merged = {"labels": dict(sa["labels"]),
                          "value": max(sa["value"], sb["value"])}
            if (fa["kind"] == "histogram" and not same_buckets):
                merged["bucket_counts"] = None
            ent["series"][key] = merged
        out[name] = ent
    return out


def merge_states(states: Dict[str, dict]) -> dict:
    """{replica: export_state} -> one canonical merged state."""
    merged: dict = {}
    for replica in sorted(states):
        merged = merge(merged, canonicalize(replica,
                                            states[replica]))
    return merged


def percentiles(samples: List[float],
                qs=(50, 90, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles — the exact formula
    obs/metrics.HistogramChild.percentiles uses, applied to a merged
    sample multiset."""
    xs = sorted(samples)
    if not xs:
        return {"p%d" % q: 0.0 for q in qs}
    n = len(xs)
    return {"p%d" % q:
            xs[min(n - 1, max(0, (n * q + 99) // 100 - 1))]
            for q in qs}


def to_json(merged: dict) -> Dict[str, dict]:
    """Merged state -> the registry `snapshot()` JSON shape (with
    fleet-wide percentiles computed from the merged windows)."""
    out: Dict[str, dict] = {}
    for name in sorted(merged):
        fam = merged[name]
        series = []
        for key in sorted(fam["series"]):
            s = fam["series"][key]
            entry: dict = {"labels": dict(s["labels"])}
            if fam["kind"] == "histogram":
                pcts = percentiles(s["samples"])
                entry.update({
                    "count": s["count"],
                    "sum": round(s["sum"], 6),
                    "p50": round(pcts["p50"], 6),
                    "p90": round(pcts["p90"], 6),
                    "p99": round(pcts["p99"], 6),
                })
            else:
                entry["value"] = s["value"]
            series.append(entry)
        out[name] = {"type": fam["kind"], "help": fam["help"],
                     "series": series}
    return out


def rollup(merged: dict, name: str,
           label: str) -> Dict[str, dict]:
    """Histogram rollup across every OTHER label: merge the sample
    windows/counts of all series sharing each value of ``label``
    (e.g. job_e2e_seconds by phase, across buckets and replicas).
    The control-plane consumer: one number per phase, fleet-wide."""
    fam = merged.get(name)
    if fam is None or fam["kind"] != "histogram":
        return {}
    acc: Dict[str, dict] = {}
    for s in fam["series"].values():
        v = str(s["labels"].get(label, ""))
        a = acc.setdefault(v, {"count": 0, "sum": 0.0,
                               "samples": []})
        a["count"] += s["count"]
        a["sum"] += s["sum"]
        a["samples"].extend(s["samples"])
    out: Dict[str, dict] = {}
    for v, a in sorted(acc.items()):
        pcts = percentiles(a["samples"])
        out[v] = {"count": a["count"], "sum": round(a["sum"], 6),
                  "p50": round(pcts["p50"], 6),
                  "p90": round(pcts["p90"], 6),
                  "p99": round(pcts["p99"], 6)}
    return out


def counter_rollup(merged: dict, name: str,
                   label: str) -> Dict[str, float]:
    """Counter rollup across every OTHER label: sum the series
    sharing each value of ``label`` (e.g. jax_dispatches_total by
    kind, across replicas) — the fleet-wide per-stage dispatch table
    presto-report renders."""
    fam = merged.get(name)
    if fam is None or fam["kind"] != "counter":
        return {}
    acc: Dict[str, float] = {}
    for s in fam["series"].values():
        v = str(s["labels"].get(label, ""))
        acc[v] = acc.get(v, 0.0) + float(s.get("value", 0.0))
    return dict(sorted(acc.items()))


def render_prometheus(merged: dict) -> str:
    """Prometheus text exposition of a merged state (the
    `GET /fleet/metrics?format=prometheus` body).  Histogram series
    whose bucket layouts could not be merged expose only _sum/_count.
    """
    lines: List[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if fam["help"]:
            lines.append("# HELP %s %s"
                         % (name, fam["help"].replace("\n", " ")))
        lines.append("# TYPE %s %s" % (name, fam["kind"]))
        for key in sorted(fam["series"]):
            s = fam["series"][key]
            labels = tuple(sorted((k, str(v)) for k, v in
                                  s["labels"].items()))
            if fam["kind"] == "histogram":
                if s.get("bucket_counts") is not None:
                    acc = 0
                    buckets = [math.inf if b is None else float(b)
                               for b in fam.get("buckets") or []]
                    for ub, c in zip(buckets, s["bucket_counts"]):
                        acc += c
                        ls = labels + (("le", _fmt(ub)),)
                        lines.append("%s_bucket%s %s"
                                     % (name, _label_suffix(ls),
                                        _fmt(acc)))
                lines.append("%s_sum%s %s"
                             % (name, _label_suffix(labels),
                                _fmt(s["sum"])))
                lines.append("%s_count%s %s"
                             % (name, _label_suffix(labels),
                                _fmt(s["count"])))
            else:
                lines.append("%s%s %s"
                             % (name, _label_suffix(labels),
                                _fmt(s["value"])))
    return "\n".join(lines) + "\n"


def snapshot_is_stale(snap: dict,
                      now: Optional[float] = None) -> bool:
    """A LIVE snapshot older than STALE_INTERVALS publish intervals:
    its publisher stopped publishing without tombstoning (wedged
    heartbeat loop, paused process, dead-but-unreaped replica).  A
    tombstone is never stale — it is the intentional final word."""
    if snap.get("tombstone"):
        return False
    now = time.time() if now is None else now
    interval = float(snap.get("interval_s")
                     or DEFAULT_SNAPSHOT_INTERVAL)
    return now - float(snap.get("ts") or 0.0) \
        > STALE_INTERVALS * interval


def aggregate(fleetdir: str, now: Optional[float] = None) -> dict:
    """One full aggregation pass over a fleet directory: load every
    snapshot, merge (tombstoned replicas keep their counters and
    histograms — that work happened — but contribute no gauges), and
    report per-replica freshness.  Stale snapshots (older than 3x
    their publish interval, not tombstoned) still merge — their
    counters are real work — but are flagged per replica and in the
    top-level ``stale_replicas`` list so consumers see the fleet
    view is partially out of date instead of silently trusting it."""
    now = time.time() if now is None else now
    snaps = load_snapshots(fleetdir)
    states: Dict[str, dict] = {}
    stale: List[str] = []
    for name, snap in snaps.items():
        state = snap.get("metrics") or {}
        if snap.get("tombstone"):
            fams = {n: f for n, f in
                    (state.get("families") or {}).items()
                    if f.get("kind") != "gauge"}
            state = {"families": fams}
        if snapshot_is_stale(snap, now):
            stale.append(name)
        states[name] = state
    return {
        "replicas": {
            name: {"ts": snap.get("ts", 0.0),
                   "pid": snap.get("pid"),
                   "service": snap.get("service"),
                   "tombstone": bool(snap.get("tombstone")),
                   "stale": name in stale,
                   "age_s": round(max(now - float(snap.get("ts")
                                                  or 0.0), 0.0), 3)}
            for name, snap in sorted(snaps.items())},
        "stale_replicas": sorted(stale),
        "merged": merge_states(states),
    }


# ----------------------------------------------------------------------
# cross-process trace joining
# ----------------------------------------------------------------------

def load_spans(paths: Iterable[str]) -> List[dict]:
    """Parse span dicts out of JSONL streams (bad lines skipped)."""
    out: List[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("span_id"):
                        rec.setdefault("_source",
                                       os.path.basename(path))
                        out.append(rec)
        except OSError:
            continue
    return out


def load_fleet_spans(fleetdir: str) -> List[dict]:
    """Every span from every process's stream under <fleet>/obs/."""
    return load_spans(sorted(glob.glob(
        os.path.join(obs_dir(fleetdir), "*.spans.jsonl"))))


def spans_by_trace(spans: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for s in spans:
        out.setdefault(str(s.get("trace_id") or ""), []).append(s)
    for trace in out.values():
        trace.sort(key=lambda s: float(s.get("start", 0.0)))
    return out


def orphan_spans(spans: List[dict]) -> List[dict]:
    """Spans whose parent_id names a span absent from the SAME trace
    — the broken-propagation signal the loadgen `-obs` verdict pins
    to zero."""
    out: List[dict] = []
    for trace in spans_by_trace(spans).values():
        ids = {s["span_id"] for s in trace}
        out += [s for s in trace
                if s.get("parent_id") and s["parent_id"] not in ids]
    return out


def merged_chrome_trace(spans: List[dict]) -> dict:
    """Span dicts from N processes -> one Chrome/Perfetto
    ``trace_event`` document: pid rows per source process, tid rows
    per (pid, thread) — the single timeline a cross-replica DAG
    renders into."""
    tids: Dict[Tuple[int, str], int] = {}
    names: Dict[int, str] = {}
    events = []
    for s in spans:
        pid = int(s.get("pid") or 0)
        names.setdefault(pid, str(s.get("_source", "pid-%d" % pid)))
        tid = tids.setdefault((pid, str(s.get("thread", ""))),
                              len(tids) + 1)
        start = float(s.get("start", 0.0))
        end = float(s.get("end", 0.0)) or start
        events.append({
            "name": s.get("name", "?"),
            "cat": "presto_tpu",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(end - start, 0.0) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": dict(s.get("attrs") or {},
                         trace_id=s.get("trace_id") or "",
                         span_id=s.get("span_id") or "",
                         parent_id=s.get("parent_id") or "",
                         status=s.get("status", "ok")),
        })
    for pid, label in names.items():
        events.append({"name": "process_name", "ph": "M",
                       "pid": pid, "tid": 0,
                       "args": {"name": label}})
    for (pid, tname), tid in tids.items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_merged_chrome(path: str, spans: List[dict]) -> str:
    atomic_write_text(path,
                      json.dumps(merged_chrome_trace(spans)) + "\n")
    return path


# ----------------------------------------------------------------------
# DAG critical-path attribution
# ----------------------------------------------------------------------

def dag_critical_path(jobs: Dict[str, dict], dag_id: str) -> dict:
    """Walk one DAG's ledger rows into a latency attribution: which
    node chain gated end-to-end latency, and inside each node how
    much was lease wait (submitted/parent-ready -> leased_at) vs
    execution (leased_at -> completed_at).  Pure function over the
    ledger's row dicts (jobs.json \"jobs\" table)."""
    rows = {jid: row for jid, row in jobs.items()
            if row.get("dag") == dag_id}
    if not rows:
        return {}
    done = {jid: row for jid, row in rows.items()
            if row.get("completed_at")}

    def parent_ready(row) -> float:
        ready = float(row.get("submitted") or 0.0)
        for pid in row.get("blocked_on") or ():
            prow = rows.get(pid)
            if prow and prow.get("completed_at"):
                ready = max(ready, float(prow["completed_at"]))
        return ready

    def node_view(jid) -> dict:
        row = rows[jid]
        leased = float(row.get("leased_at") or 0.0)
        completed = float(row.get("completed_at") or 0.0)
        ready = parent_ready(row)
        return {
            "job_id": jid,
            "kind": str((row.get("spec") or {}).get("kind",
                                                    "survey")),
            "state": row.get("state"),
            "wait_s": round(max(leased - ready, 0.0), 6)
            if leased else None,
            "run_s": round(max(completed - leased, 0.0), 6)
            if leased and completed else None,
        }

    submitted = min(float(r.get("submitted") or 0.0)
                    for r in rows.values())
    path: List[str] = []
    if done:
        cur = max(done, key=lambda j: float(done[j]["completed_at"]))
        seen = set()
        while cur and cur not in seen:
            seen.add(cur)
            path.append(cur)
            parents = [p for p in rows.get(cur, {}).get("blocked_on")
                       or () if p in done]
            cur = max(parents,
                      key=lambda p: float(done[p]["completed_at"])) \
                if parents else None
        path.reverse()
    e2e = (max(float(r["completed_at"]) for r in done.values())
           - submitted) if done else None
    nodes = [node_view(jid) for jid in path]
    wait = sum(n["wait_s"] or 0.0 for n in nodes)
    run = sum(n["run_s"] or 0.0 for n in nodes)
    return {
        "dag_id": dag_id,
        "n_nodes": len(rows),
        "n_done": len(done),
        "e2e_s": round(e2e, 6) if e2e is not None else None,
        "critical_path": nodes,
        "wait_s": round(wait, 6),
        "run_s": round(run, 6),
        "wait_share": round(wait / e2e, 4) if e2e else None,
        "run_share": round(run / e2e, 4) if e2e else None,
    }
