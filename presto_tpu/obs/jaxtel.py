"""JAX compile/device telemetry (obs layer).

The expensive, invisible half of a TPU pipeline is everything XLA does
between our Python lines: compiles, host<->device transfers, buffer
donation, HBM occupancy.  This module gives those events names on the
shared metrics registry so the plan cache (serve/plancache.py) and the
survey driver (pipeline/survey.py) report them per plan bucket:

  jax_compiles_total{kind}        executables built (plan-cache misses)
  jax_compile_seconds{kind}       build wall time histogram
  jax_device_put_bytes_total      host -> device upload volume
  jax_device_get_bytes_total      device -> host download volume
  jax_donated_bytes_total         buffers handed to XLA via donation
  jax_live_buffer_bytes           current live device allocation
  jax_live_buffer_hwm_bytes       high-water mark of the above

The dispatch counter additionally joins with obs/costmodel's harvested
per-dispatch unit costs (kernel_flops_total{kind} /
kernel_hbm_bytes_total{kind}) so every stage's silicon cost
accumulates next to its launch count.

Every helper takes the Observability handle and is one branch when
observability is disabled; all jax imports are local and guarded so
the module works (as a no-op) on hosts without a usable backend.
"""

from __future__ import annotations

from typing import Optional


def current_device_id() -> Optional[str]:
    """Stable identity of the default device ('TPU_0(process=0,...)',
    'TFRT_CPU_0', ...) or None when no backend is reachable.  The plan
    cache records this per compiled executable so a device reset can
    evict exactly the poisoned bindings."""
    try:
        import jax
        d = jax.devices()[0]
        return "%s_%d" % (d.platform, d.id)
    except Exception:
        return None


def note_compile(obs, kind: str, seconds: float,
                 key=None, device: Optional[str] = None,
                 compiled=None) -> None:
    """One executable built: count it, time it, remember it.  Call
    sites that hold the compiled object (or anything exposing
    ``cost_analysis``) pass it as ``compiled`` so obs/costmodel can
    harvest the per-dispatch FLOP/byte unit cost at the same moment
    the compile is booked; plan bundles without one are skipped
    silently."""
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "jax_compiles_total", "XLA executables built",
        ("kind",)).labels(kind=kind).inc()
    obs.metrics.histogram(
        "jax_compile_seconds", "XLA compile wall time",
        ("kind",)).labels(kind=kind).observe(seconds)
    obs.flightrec.add("compile", plan_kind=kind,
                      seconds=round(float(seconds), 4),
                      key=repr(key) if key is not None else "",
                      device=device or "")
    if compiled is not None:
        from presto_tpu.obs import costmodel
        costmodel.note_compiled(obs, kind, compiled)


def note_dispatch(obs, kind: str, n: int = 1) -> None:
    """One batched device-chain dispatch: a single rFFT / accel-scan /
    single-pulse program launch covering however many trials ride its
    batch axis.  The stacked serve executor's whole win is fewer of
    these for the same job count (docs/SERVING.md, stacked batches) —
    `jax_dispatches_total{kind}` is the counter the stacked-vs-per-job
    A/B pins."""
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "jax_dispatches_total",
        "Batched device-chain dispatches (rFFT/search/single-pulse "
        "program launches)", ("kind",)).labels(kind=kind).inc(int(n))
    # the cost join: dispatches x harvested per-dispatch unit cost ->
    # kernel_flops_total{kind} / kernel_hbm_bytes_total{kind} + the
    # current span's flops/hbm_bytes attrs (obs/costmodel)
    from presto_tpu.obs import costmodel
    costmodel.attribute_dispatch(obs, kind, int(n))


def note_put(obs, nbytes: int) -> None:
    """Host -> device upload volume."""
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "jax_device_put_bytes_total",
        "Bytes uploaded host to device").inc(int(nbytes))


def note_get(obs, nbytes: int) -> None:
    """Device -> host download volume."""
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "jax_device_get_bytes_total",
        "Bytes downloaded device to host").inc(int(nbytes))


def note_donation(obs, nbytes: int) -> None:
    """Buffer bytes donated to XLA (freed for reuse in-kernel)."""
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "jax_donated_bytes_total",
        "Buffer bytes donated to XLA").inc(int(nbytes))


def transfer_snapshot(obs) -> dict:
    """Current host<->device transfer/donation/compile totals off the
    shared registry — the attribution block the fused pipeline's
    before/after comparison reads (bench.py inclusive_breakdown, the
    survey's end-of-run span).  Returns zeros when observability is
    disabled, so callers can diff snapshots unconditionally."""
    out = {"put_bytes": 0, "get_bytes": 0, "donated_bytes": 0,
           "compiles": 0, "compile_seconds": 0.0, "dispatches": 0,
           "kernel_flops": 0.0, "kernel_hbm_bytes": 0.0}
    if obs is None or not obs.enabled:
        return out
    reg = obs.metrics
    out["dispatches"] = int(reg.counter(
        "jax_dispatches_total",
        "Batched device-chain dispatches (rFFT/search/single-pulse "
        "program launches)", ("kind",)).total())
    out["put_bytes"] = int(reg.counter(
        "jax_device_put_bytes_total",
        "Bytes uploaded host to device").value)
    out["get_bytes"] = int(reg.counter(
        "jax_device_get_bytes_total",
        "Bytes downloaded device to host").value)
    out["donated_bytes"] = int(reg.counter(
        "jax_donated_bytes_total",
        "Buffer bytes donated to XLA").value)
    comp = reg.counter("jax_compiles_total",
                       "XLA executables built", ("kind",))
    hist = reg.histogram("jax_compile_seconds",
                         "XLA compile wall time", ("kind",))
    out["compiles"] = int(comp.total())
    out["compile_seconds"] = float(
        sum(h.sum for _lbl, h in hist.children()))
    for snap_key, name in (("kernel_flops", "kernel_flops_total"),
                           ("kernel_hbm_bytes",
                            "kernel_hbm_bytes_total")):
        fam = reg.get(name)
        out[snap_key] = float(fam.total()) if fam is not None else 0.0
    return out


def sample_live_buffers(obs) -> Optional[int]:
    """Sample current live device-buffer bytes into the gauge pair
    (current + high-water mark).  Prefers the backend's memory_stats
    (TPU/GPU); falls back to summing jax.live_arrays() nbytes (CPU).
    Returns the sampled byte count, or None when unavailable."""
    if obs is None or not obs.enabled:
        return None
    nbytes: Optional[int] = None
    try:
        import jax
        stats = getattr(jax.devices()[0], "memory_stats", None)
        if callable(stats):
            s = stats() or {}
            if "bytes_in_use" in s:
                nbytes = int(s["bytes_in_use"])
        if nbytes is None:
            nbytes = sum(int(getattr(a, "nbytes", 0))
                         for a in jax.live_arrays())
    except Exception:
        return None
    obs.metrics.gauge(
        "jax_live_buffer_bytes",
        "Live device buffer bytes (last sample)").set(nbytes)
    obs.metrics.gauge(
        "jax_live_buffer_hwm_bytes",
        "Live device buffer bytes high-water mark").set_max(nbytes)
    return nbytes
