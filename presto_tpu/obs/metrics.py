"""Process-wide metrics registry (obs layer).

One vocabulary for every counter the system keeps: the serve layer's
job/queue/plan accounting, the survey driver's stage timings, ingest
quality tallies, and the JAX compile/transfer telemetry all register
Counter/Gauge/Histogram instruments here instead of growing private
int fields.  The registry renders two ways:

  * Prometheus text exposition (``render_prometheus``) — what a
    scrape of ``GET /metrics`` with ``Accept: text/plain`` returns;
  * a JSON snapshot (``snapshot``) — the machine-readable twin used
    by ``presto-report`` and tests.

Thread-safety is per-child: instruments take one small lock around a
few arithmetic ops, never around user code, so recording from the
scheduler thread, HTTP handler threads, and the survey driver at once
is safe.  Disabled registries cost one branch per record call — a
survey run without observability must be indistinguishable from an
uninstrumented one.

Histograms keep classic cumulative le-buckets for exposition *and* a
bounded window of recent raw samples for nearest-rank percentiles —
the same formula ``utils/timing.LatencyStats`` has always used, which
is now a thin view over these histograms (one source of truth).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

#: default latency buckets (seconds) — wide enough for both a single
#: kernel launch and a full multi-DM survey stage
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   math.inf)

#: default per-histogram-child sample window for percentiles
DEFAULT_WINDOW = 2048


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as integers."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return "%d" % int(f)
    return repr(f)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _label_suffix(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _escape_label(v))
                             for k, v in labels)


class _Child:
    """One (metric, label-values) time series."""

    def __init__(self, family: "_Family",
                 labels: Tuple[Tuple[str, str], ...]):
        self._family = family
        self._labels = labels
        self._lock = threading.Lock()


class CounterChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._family.registry.enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class GaugeChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        if not self._family.registry.enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._family.registry.enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_max(self, value: float) -> None:
        """High-water-mark update (live-buffer peaks etc.)."""
        if not self._family.registry.enabled:
            return
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class HistogramChild(_Child):
    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._count = 0
        self._sum = 0.0
        self._bucket_counts = [0] * len(family.buckets)
        self._window: deque = deque(maxlen=family.window)

    def observe(self, value: float) -> None:
        if not self._family.registry.enabled:
            return
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            for i, ub in enumerate(self._family.buckets):
                if v <= ub:
                    self._bucket_counts[i] += 1
                    break
            self._window.append(v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def samples(self) -> List[float]:
        """The current percentile window (recent raw samples)."""
        with self._lock:
            return list(self._window)

    def percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Nearest-rank percentiles over the sample window — the exact
        formula LatencyStats has always reported."""
        xs = sorted(self.samples())
        if not xs:
            return {"p%d" % q: 0.0 for q in qs}
        n = len(xs)
        return {"p%d" % q:
                xs[min(n - 1, max(0, (n * q + 99) // 100 - 1))]
                for q in qs}

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        with self._lock:
            counts = list(self._bucket_counts)
        out, acc = [], 0
        for ub, c in zip(self._family.buckets, counts):
            acc += c
            out.append((ub, acc))
        return out


class _Family:
    """A named metric plus its per-label-value children."""

    kind = "untyped"
    child_cls = _Child

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str, labelnames: Tuple[str, ...]):
        self.registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()  # presto-lint: guards(_children)
        self._children: Dict[Tuple[Tuple[str, str], ...], _Child] = {}
        if not self.labelnames:
            self._default = self._make_child(())
        else:
            self._default = None

    def _make_child(self, labels):  # presto-lint: holds(_lock)
        child = self.child_cls(self, labels)
        self._children[labels] = child
        return child

    def labels(self, **kv) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                "%s expects labels %r, got %r"
                % (self.name, self.labelnames, tuple(kv)))
        key = tuple((k, str(kv[k])) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child(key)
            return child

    def children(self) -> List[Tuple[Tuple[Tuple[str, str], ...],
                                     _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # label-less convenience: the family proxies its single child
    def _solo(self) -> _Child:
        if self._default is None:
            raise ValueError("%s has labels %r; use .labels()"
                             % (self.name, self.labelnames))
        return self._default


class CounterFamily(_Family):
    kind = "counter"
    child_cls = CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    @property
    def value(self) -> float:
        return self._solo().value

    def total(self) -> float:
        """Sum over every label combination."""
        return sum(c.value for _, c in self.children())


class GaugeFamily(_Family):
    kind = "gauge"
    child_cls = GaugeChild

    def set(self, value: float) -> None:
        self._solo().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set_max(self, value: float) -> None:
        self._solo().set_max(value)

    @property
    def value(self) -> float:
        return self._solo().value


class HistogramFamily(_Family):
    kind = "histogram"
    child_cls = HistogramChild

    def __init__(self, registry, name, help, labelnames,
                 buckets=DEFAULT_BUCKETS, window=DEFAULT_WINDOW):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or bs[-1] != math.inf:
            bs = bs + (math.inf,)
        self.buckets = bs
        self.window = int(window)
        super().__init__(registry, name, help, labelnames)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    @property
    def count(self) -> int:
        return self._solo().count

    @property
    def sum(self) -> float:
        return self._solo().sum

    def percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        return self._solo().percentiles(qs)


class MetricsRegistry:
    """Get-or-create instrument registry.

    Re-registering a name returns the existing family (so independent
    components sharing a registry converge on one time series), but a
    kind or label mismatch is a programming error and raises.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()  # presto-lint: guards(_families)
        self._families: "Dict[str, _Family]" = {}

    # -- registration -------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        "metric %r already registered as %s%r"
                        % (name, fam.kind, fam.labelnames))
                return fam
            fam = cls(self, name, help, tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> CounterFamily:
        return self._get_or_create(CounterFamily, name, help,
                                   tuple(labelnames))

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> GaugeFamily:
        return self._get_or_create(GaugeFamily, name, help,
                                   tuple(labelnames))

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets=DEFAULT_BUCKETS,
                  window: int = DEFAULT_WINDOW) -> HistogramFamily:
        return self._get_or_create(HistogramFamily, name, help,
                                   tuple(labelnames), buckets=buckets,
                                   window=window)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition ---------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append("# HELP %s %s"
                             % (fam.name, fam.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (fam.name, fam.kind))
            for labels, child in fam.children():
                if isinstance(child, HistogramChild):
                    for ub, acc in child.cumulative_buckets():
                        ls = labels + (("le", _fmt(ub)),)
                        lines.append("%s_bucket%s %s"
                                     % (fam.name, _label_suffix(ls),
                                        _fmt(acc)))
                    lines.append("%s_sum%s %s"
                                 % (fam.name, _label_suffix(labels),
                                    _fmt(child.sum)))
                    lines.append("%s_count%s %s"
                                 % (fam.name, _label_suffix(labels),
                                    _fmt(child.count)))
                else:
                    lines.append("%s%s %s"
                                 % (fam.name, _label_suffix(labels),
                                    _fmt(child.value)))
        return "\n".join(lines) + "\n"

    def export_state(self) -> Dict[str, dict]:
        """Full, *mergeable* registry state (the fleet-aggregation
        wire format, obs/fleetagg.py).  Unlike `snapshot`, histograms
        carry their raw bucket counts AND the percentile sample
        window, so N replicas' exports can be bucket-merged into one
        fleet-wide histogram whose nearest-rank percentiles equal a
        single shared registry's.  `inf` bucket bounds are encoded as
        None (strict-JSON safe)."""
        fams: Dict[str, dict] = {}
        for fam in self.families():
            series = []
            for labels, child in fam.children():
                entry: dict = {"labels": dict(labels)}
                if isinstance(child, HistogramChild):
                    with child._lock:
                        entry.update({
                            "count": child._count,
                            "sum": child._sum,
                            "bucket_counts": list(
                                child._bucket_counts),
                            "samples": list(child._window),
                        })
                else:
                    entry["value"] = child.value
                series.append(entry)
            ent = {"kind": fam.kind, "help": fam.help,
                   "labelnames": list(fam.labelnames),
                   "series": series}
            if isinstance(fam, HistogramFamily):
                ent["buckets"] = [None if b == math.inf else b
                                  for b in fam.buckets]
                ent["window"] = fam.window
            fams[fam.name] = ent
        return {"families": fams}

    def snapshot(self) -> Dict[str, dict]:
        """JSON twin of the exposition (presto-report, tests)."""
        out: Dict[str, dict] = {}
        for fam in self.families():
            series = []
            for labels, child in fam.children():
                entry: dict = {"labels": dict(labels)}
                if isinstance(child, HistogramChild):
                    pcts = child.percentiles()
                    entry.update({
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "p50": round(pcts["p50"], 6),
                        "p90": round(pcts["p90"], 6),
                        "p99": round(pcts["p99"], 6),
                    })
                else:
                    entry["value"] = child.value
                series.append(entry)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out
