"""XLA cost attribution per plan kind (obs layer).

The obs stack sees processes, fleets, and SLOs but was blind below the
dispatch boundary: ``jax_dispatches_total{kind}`` counts how often each
jitted stage launches, not what a launch *costs the silicon*.  This
module harvests XLA's own cost model — ``Compiled.cost_analysis()`` /
``memory_analysis()`` (falling back to the HLO-level
``Lowered.cost_analysis()`` where backend compile is unavailable) —
once per (plan kind, input signature), and joins the per-dispatch unit
cost with the existing dispatch accounting so every survey/serve stage
gets cumulative FLOPs, HBM bytes-accessed, and operational intensity:

  kernel_flops_total{kind}        cumulative FLOPs attributed per kind
  kernel_hbm_bytes_total{kind}    cumulative bytes-accessed per kind
  cost_model_unavailable{reason}  harvest failures (backend/version
                                  gaps) — degraded, never a crash

Harvest points:

  * ``probe(obs, kind, fn, *args)`` at the dispatch sites that already
    call ``jaxtel.note_dispatch`` (dedisp / rfft_batch / accel_search /
    sp_search): AOT-lowers the *exact* jitted program about to run,
    under an ``obs:roofline-probe`` span, once per shape;
  * ``jaxtel.note_compile(..., compiled=...)``: plan-cache and AOT
    call sites hand over anything that quacks like a compiled
    executable (has ``cost_analysis``); non-harvestable plan objects
    are silently skipped (absence is not a backend failure).

Every dispatch then attributes ``unit * n`` onto the counters AND onto
the current span's ``flops``/``hbm_bytes`` attributes, so the Perfetto
export carries per-chunk silicon cost.  ``Observability.flush`` writes
the book as ``<workdir>/kernel_costs.json`` (schema-versioned), the
file ``presto-report`` renders as the roofline section and ``bench.py``
embeds as ``inclusive_breakdown.kernel_costs``.

Degradation contract (pinned by tests/test_costmodel.py): any backend
or jax version where cost analysis returns ``None``, raises, or is
missing entirely yields a ``cost_model_unavailable{reason}`` count and
an explicit "(unavailable)" report row — never an exception on the
search path.  ``PRESTO_TPU_COST=0`` disables harvesting outright.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

#: kernel_costs.json schema (bumping orphans old files, never crashes
#: a reader — presto-report treats a stale schema as absent)
COSTS_SCHEMA = 1

#: env kill switch: PRESTO_TPU_COST=0 disables all harvesting
ENV_SWITCH = "PRESTO_TPU_COST"


def enabled() -> bool:
    return os.environ.get(ENV_SWITCH, "1") != "0"


# ----------------------------------------------------------------------
# the per-handle cost book
# ----------------------------------------------------------------------

class KindCost:
    """Per-dispatch unit cost of one plan kind's compiled program."""

    __slots__ = ("kind", "flops", "hbm_bytes", "peak_bytes",
                 "argument_bytes", "output_bytes", "source",
                 "harvested_at")

    def __init__(self, kind: str, flops: float, hbm_bytes: float,
                 peak_bytes: Optional[int] = None,
                 argument_bytes: Optional[int] = None,
                 output_bytes: Optional[int] = None,
                 source: str = "compiled"):
        self.kind = kind
        self.flops = float(flops)
        self.hbm_bytes = float(hbm_bytes)
        self.peak_bytes = peak_bytes
        self.argument_bytes = argument_bytes
        self.output_bytes = output_bytes
        self.source = source
        self.harvested_at = time.time()

    def to_json(self) -> dict:
        return {
            "flops_per_dispatch": self.flops,
            "hbm_bytes_per_dispatch": self.hbm_bytes,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "source": self.source,
        }


class CostBook:
    """Thread-safe registry of per-kind unit costs on one
    Observability handle.  A kind's unit cost is the LAST successful
    harvest (re-probes with a new shape update it — attribution tracks
    the geometry actually in flight); failed (kind, signature) pairs
    are remembered so a broken backend is asked exactly once per
    shape."""

    def __init__(self):
        self._lock = threading.Lock()  # presto-lint: guards(_units, _tried, _pending)
        self._units: Dict[str, KindCost] = {}
        self._tried: set = set()
        # dispatches counted before their kind's first harvest landed
        # (e.g. the survey notes "accel_search" just before the call
        # that probes it) — backfilled into the counters at record()
        self._pending: Dict[str, int] = {}

    def seen(self, kind: str, sig) -> bool:
        with self._lock:
            return (kind, sig) in self._tried

    def mark(self, kind: str, sig) -> None:
        with self._lock:
            self._tried.add((kind, sig))

    def record(self, unit: KindCost) -> int:
        """Install a unit cost; returns how many earlier dispatches
        of this kind were waiting for it (the caller backfills the
        counters)."""
        with self._lock:
            self._units[unit.kind] = unit
            return self._pending.pop(unit.kind, 0)

    def defer(self, kind: str, n: int) -> None:
        with self._lock:
            self._pending[kind] = self._pending.get(kind, 0) + n

    def unit(self, kind: str) -> Optional[KindCost]:
        with self._lock:
            return self._units.get(kind)

    def units(self) -> Dict[str, KindCost]:
        with self._lock:
            return dict(self._units)


def book(obs) -> Optional[CostBook]:
    """The handle's cost book (lazily attached); None when the handle
    is disabled or harvesting is switched off."""
    if obs is None or not getattr(obs, "enabled", False) \
            or not enabled():
        return None
    bk = getattr(obs, "_cost_book", None)
    if bk is None:
        bk = obs._cost_book = CostBook()
    return bk


# ----------------------------------------------------------------------
# harvesting
# ----------------------------------------------------------------------

def _signature(args, kwargs) -> tuple:
    """Cheap shape/dtype identity of a call (what decides whether a
    kind needs re-probing)."""
    def one(a):
        shp = getattr(a, "shape", None)
        if shp is not None:
            return (tuple(shp), str(getattr(a, "dtype", "?")))
        if isinstance(a, (list, tuple)):
            return tuple(one(x) for x in a)
        return repr(a)[:64]
    return (tuple(one(a) for a in args),
            tuple(sorted((k, one(v)) for k, v in kwargs.items())))


def _cost_dict(raw) -> Optional[dict]:
    """Normalize cost_analysis() output across jax versions: older
    jaxlibs return a one-element list of dicts, newer return the dict
    itself; anything else is unusable."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    return raw if isinstance(raw, dict) else None


def _note_unavailable(obs, reason: str) -> None:
    if obs is None or not obs.enabled:
        return
    obs.metrics.counter(
        "cost_model_unavailable",
        "Cost-model harvest failures (backend/version gaps)",
        ("reason",)).labels(reason=reason).inc()


def harvest_compiled(compiled) -> KindCost:
    """Unit cost off a compiled executable (jax ``Compiled`` or
    anything with the same duck type).  Raises on any gap — callers
    route failures through the unavailable counter."""
    cost = _cost_dict(compiled.cost_analysis())
    if cost is None or "flops" not in cost:
        raise ValueError("cost_analysis returned no flops")
    peak = arg_b = out_b = None
    try:
        mem = compiled.memory_analysis()
        arg_b = int(mem.argument_size_in_bytes)
        out_b = int(mem.output_size_in_bytes)
        peak = (arg_b + out_b + int(mem.temp_size_in_bytes)
                - int(getattr(mem, "alias_size_in_bytes", 0)))
    except Exception:
        pass                     # memory stats are best-effort extras
    return KindCost("?", flops=max(float(cost.get("flops", 0.0)), 0.0),
                    hbm_bytes=max(
                        float(cost.get("bytes accessed", 0.0)), 0.0),
                    peak_bytes=peak, argument_bytes=arg_b,
                    output_bytes=out_b, source="compiled")


def probe(obs, kind: str, fn, *args, **kwargs) -> Optional[KindCost]:
    """Harvest the unit cost of the jitted callable ``fn`` for this
    call signature, once per (kind, signature), under an
    ``obs:roofline-probe`` span.  ``fn`` must be a jax-jitted function
    (has ``.lower``); the probe only lowers/compiles — it never
    executes, so instrumented paths stay byte-identical.

    Degrades (``cost_model_unavailable{reason}`` + None) when the
    backend/version cannot lower, compile, or cost-analyze."""
    bk = book(obs)
    if bk is None:
        return None
    sig = _signature(args, kwargs)
    if bk.seen(kind, sig):
        return bk.unit(kind)
    bk.mark(kind, sig)
    sp = obs.span("obs:roofline-probe", kind=kind)
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            raise TypeError("not a jitted callable")
        lowered = lower(*args, **kwargs)
        try:
            unit = harvest_compiled(lowered.compile())
        except Exception:
            # backend compile (or compiled-level analysis) gap:
            # degrade to the HLO-level estimate
            cost = _cost_dict(lowered.cost_analysis())
            if cost is None or "flops" not in cost:
                raise
            unit = KindCost(
                kind, flops=max(float(cost.get("flops", 0.0)), 0.0),
                hbm_bytes=max(
                    float(cost.get("bytes accessed", 0.0)), 0.0),
                source="lowered")
        unit.kind = kind
    except Exception as e:
        _note_unavailable(obs, type(e).__name__)
        sp.finish("error: %s" % type(e).__name__)
        return None
    _install(obs, bk, unit)
    sp.finish()
    return unit


def _install(obs, bk: CostBook, unit: KindCost) -> None:
    """Record a unit and backfill any dispatches counted before the
    harvest landed (counters only — their spans are long gone)."""
    pending = bk.record(unit)
    if pending:
        _bump_counters(obs, unit.kind, unit, pending)


def note_compiled(obs, kind: str, compiled) -> Optional[KindCost]:
    """``jaxtel.note_compile``'s harvest hook: record the unit cost of
    a freshly built executable when the call site can hand one over.
    Objects without a ``cost_analysis`` (e.g. plan-cache AccelSearch
    bundles) are skipped silently — only a *failed* harvest attempt
    counts as unavailable."""
    bk = book(obs)
    if bk is None or compiled is None:
        return None
    if not hasattr(compiled, "cost_analysis"):
        return None
    sp = obs.span("obs:roofline-probe", kind=kind)
    try:
        unit = harvest_compiled(compiled)
        unit.kind = kind
    except Exception as e:
        _note_unavailable(obs, type(e).__name__)
        sp.finish("error: %s" % type(e).__name__)
        return None
    _install(obs, bk, unit)
    sp.finish()
    return unit


# ----------------------------------------------------------------------
# the dispatch join
# ----------------------------------------------------------------------

def _bump_counters(obs, kind: str, unit: KindCost, n: int) -> None:
    reg = obs.metrics
    reg.counter("kernel_flops_total",
                "Cumulative XLA-modeled FLOPs per plan kind",
                ("kind",)).labels(kind=kind).inc(unit.flops * n)
    reg.counter("kernel_hbm_bytes_total",
                "Cumulative XLA-modeled bytes-accessed per plan kind",
                ("kind",)).labels(kind=kind).inc(unit.hbm_bytes * n)


def attribute_dispatch(obs, kind: str, n: int = 1) -> None:
    """Join one (batched) dispatch with its kind's unit cost:
    cumulative counters plus flops/hbm_bytes attributes on the current
    span (the chunk spans the survey already opens), so the Perfetto
    export carries silicon cost per chunk.  A dispatch counted before
    its kind's first harvest is deferred and backfilled into the
    counters when the unit lands (the survey notes "accel_search"
    just before the call that probes it).  One dict lookup + two
    counter incs when a unit exists; one branch otherwise."""
    bk = book(obs)
    if bk is None:
        return
    unit = bk.unit(kind)
    if unit is None:
        bk.defer(kind, n)
        return
    _bump_counters(obs, kind, unit, n)
    sp = obs.tracer.current()
    if sp is not None:
        sp.set_attr("flops",
                    sp.attrs.get("flops", 0.0) + unit.flops * n)
        sp.set_attr("hbm_bytes",
                    sp.attrs.get("hbm_bytes", 0.0)
                    + unit.hbm_bytes * n)


# ----------------------------------------------------------------------
# snapshot / export
# ----------------------------------------------------------------------

def _counter_by_label(obs, name: str, label: str) -> Dict[str, float]:
    fam = obs.metrics.get(name)
    if fam is None:
        return {}
    out: Dict[str, float] = {}
    for labels, child in fam.children():
        key = dict(labels).get(label, "")
        out[key] = out.get(key, 0.0) + child.value
    return out


def snapshot(obs) -> dict:
    """The cost book joined with the live dispatch counters — the
    ``kernel_costs`` block of serve /metrics and bench.py.  Returns
    ``{}`` when nothing was harvested (disabled handles included)."""
    bk = book(obs)
    if bk is None:
        return {}
    units = bk.units()
    unavailable = _counter_by_label(obs, "cost_model_unavailable",
                                    "reason")
    if not units and not unavailable:
        return {}
    dispatches = _counter_by_label(obs, "jax_dispatches_total", "kind")
    flops_tot = _counter_by_label(obs, "kernel_flops_total", "kind")
    bytes_tot = _counter_by_label(obs, "kernel_hbm_bytes_total",
                                  "kind")
    kinds = {}
    for kind in sorted(set(units) | set(dispatches)):
        unit = units.get(kind)
        ent: dict = {"dispatches": int(dispatches.get(kind, 0))}
        if unit is not None:
            ent.update(unit.to_json())
            ent["flops_total"] = flops_tot.get(kind, 0.0)
            ent["hbm_bytes_total"] = bytes_tot.get(kind, 0.0)
            if unit.hbm_bytes > 0:
                ent["intensity"] = unit.flops / unit.hbm_bytes
        kinds[kind] = ent
    return {
        "schema": COSTS_SCHEMA,
        "kinds": kinds,
        "unavailable": {k: int(v)
                        for k, v in sorted(unavailable.items())},
    }


def write_costs(obs, dirpath: str) -> Optional[str]:
    """Export the book as ``<dirpath>/kernel_costs.json`` (atomic;
    no-op when nothing was harvested).  Peaks ride along when the
    roofline microbench has already cached them for this fingerprint —
    the export never runs device work itself."""
    snap = snapshot(obs)
    if not snap:
        return None
    from presto_tpu.obs import roofline
    try:
        snap["peaks"] = roofline.device_peaks(obs=obs, measure=False)
    except Exception:
        snap["peaks"] = None
    import json
    from presto_tpu.io.atomic import atomic_write_text
    path = os.path.join(dirpath, "kernel_costs.json")
    atomic_write_text(path, json.dumps(snap, indent=1,
                                       sort_keys=True) + "\n")
    return path


def load_costs(dirpath: str) -> Optional[dict]:
    """Defensive read of a workdir's kernel_costs.json (None on
    absence, corruption, or a stale schema)."""
    import json
    try:
        with open(os.path.join(dirpath, "kernel_costs.json")) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(raw, dict) or raw.get("schema") != COSTS_SCHEMA:
        return None
    return raw
