"""The observability name catalog (the contract tools/obs_lint.py
enforces).

Every survey stage, chaos kill point, serve event kind, and metric
name the codebase emits must be listed here, and docs/OBSERVABILITY.md
documents exactly this catalog.  The linter cross-checks the *source*
(pipeline/survey.py, serve/*.py) against these sets, so adding a stage
or a scheduler transition without registering (and documenting) its
telemetry fails CI instead of silently shipping an unobservable code
path.
"""

from __future__ import annotations

#: survey stages — every `timer.mark("<stage>")` in pipeline/survey.py
#: (each becomes a `survey_stage_seconds{stage=...}` sample and a span)
SURVEY_STAGES = frozenset({
    "rfifind",
    "ddplan",
    "prepsubband",
    "realfft",
    "zapbirds",
    "accelsearch",
    "realfft+accelsearch (fused)",
    "sift",
    "prepfold",
    "single_pulse",
})

#: chaos kill points — every `_chaos(cfg, "<point>")` in
#: pipeline/survey.py (each is recorded in the flight recorder before
#: the injector may fire, so a dump's last record names the kill)
KILL_POINTS = frozenset({
    "pre-rfifind",
    "post-rfifind",
    "pre-prepsubband",
    "prepsubband-method",
    "elastic-method",
    "post-prepsubband",
    "seam-handoff",
    "shard-seam-handoff",
    "sp-seam-chunk",
    "zapbirds-file",
    "fft-chunk",
    "fused-chunk",
    "sharded-fused-chunk",
    "accel-chunk",
    "pre-sift",
    "post-sift",
    "fold-cand",
    "pre-singlepulse",
    "post-survey",
})

#: elastic-cluster kill points — every `self._point("<point>")` in
#: parallel/elastic.py (the multi-host analog of KILL_POINTS: each is
#: flight-recorded before the injector may fire, and
#: tools/multihost_chaos.py kills/stalls real cluster members at them)
CLUSTER_KILL_POINTS = frozenset({
    "shard-leased",
    "shard-computed",
    "pre-shard-commit",
    "post-shard-commit",
    "post-epoch-bump",
})

#: elastic-cluster event kinds — every `obs.event(...)` /
#: `self._event(...)` in parallel/elastic.py and
#: pipeline/shardledger.py (the flight-recorder vocabulary of a
#: worker-loss recovery: lease grants, redo admissions, epoch bumps,
#: fenced zombie writes, membership changes)
CLUSTER_EVENTS = frozenset({
    "chaos-point",
    "cluster-join",
    "host-dead",
    "epoch-bump",
    "mesh-reform",
    "barrier-timeout",
    "shard-lease",
    "shard-done",
    "shard-redo",
    "stale-write-rejected",
})

#: serve event kinds — every `events.emit("<kind>", ...)` in
#: presto_tpu/serve/*.py ("heartbeat" is emitted by the EventLog's own
#: heartbeat thread so /events subscribers can tell a quiet service
#: from a dead one)
SERVE_EVENTS = frozenset({
    "enqueue",
    "schedule",
    "execute",
    "retry",
    "degrade",
    "complete",
    "fail",
    "park",
    "compile",
    "evict",
    "plan-evict",
    "scheduler-error",
    "http",
    "heartbeat",
})

#: fleet-serving event kinds — the multi-replica vocabulary of
#: serve/jobledger.py (ledger lease/commit/fence flight-recorder
#: events, via the generic LeaseLedger EV_* bindings), serve/fleet.py
#: (replica lifecycle on the service event log), and serve/router.py
#: (admission-control rejections).  Enforced BOTH directions by
#: obs_lint check 10: the fleet recovery path may not emit
#: unregistered kinds, and the catalog may not list dead ones.
FLEET_EVENTS = frozenset({
    "job-lease",
    "job-done",
    "job-redo",
    "job-failed",
    "stale-result-rejected",
    "replica-dead",
    "fleet-epoch-bump",
    "quota-exceeded",
    "shed",
    "fleet-join",
    "fleet-drain",
    "fleet-tombstone",
    "fleet-pump-error",
    "router-poll-error",
    "fleet-idle-tune",
    "fleet-obs-snapshot",
    "fleet-chaos-point",
})

#: fleet-observability event kinds (subset of FLEET_EVENTS; obs_lint
#: check 13 pins them BOTH directions against serve/fleet.py +
#: serve/router.py + obs/fleetagg.py): the snapshot publication that
#: feeds `GET /fleet/metrics`, and the recorded-BEFORE-fire chaos
#: stamp that guarantees a killed replica's flight-recorder dump
#: names its kill point (batch-leased / fold-fanout included)
FLEET_OBS_EVENTS = frozenset({
    "fleet-obs-snapshot",
    "fleet-chaos-point",
})

#: fleet-observability span names — the router's admission-time root
#: spans whose SpanContext is stamped into the ledger row so the
#: leasing replica resumes the SAME trace (subset of SERVE_SPANS;
#: obs_lint check 13, both directions, `fleet:` prefix pinned)
FLEET_SPANS = frozenset({
    "fleet:submit",
    "fleet:dag-submit",
})

#: fleet-observability metrics (obs_lint check 13, both directions):
#: every `fleet_obs_*` name plus the end-to-end job decomposition
#: histogram the control-plane item consumes
FLEET_OBS_METRICS = frozenset({
    "fleet_obs_snapshots_total",
    "fleet_obs_aggregations_total",
    "job_e2e_seconds",
})

#: SLO-observatory event kinds — the decision-signal vocabulary of
#: the serving-economics layer (obs/slo.py evaluation surfaced by
#: serve/router.py): a multi-window burn-rate alert's rising edge,
#: and every change of the advisory wanted-replica count — the event
#: stream a supervisor (or tools/fleet_chaos.py in reverse) replays
#: decisions from.  Enforced BOTH directions by obs_lint check 14.
SLO_EVENTS = frozenset({
    "slo-burn-alert",
    "slo-scale-advice",
})

#: SLO-observatory span names (subset of SERVE_SPANS; check 14 both
#: directions): the router's per-pass evaluation over the durable
#: usage ledger
SLO_SPANS = frozenset({
    "slo:evaluate",
})

#: SLO-observatory metrics (obs_lint check 14, both directions,
#: subset of METRICS): device-seconds metering at the fence-checked
#: commit (serve/jobledger.py) and the router's budget/burn/scale
#: gauges — the signals the remaining control-plane actuation
#: (autoscaler, device-seconds admission) will consume
SLO_METRICS = frozenset({
    "slo_device_seconds_total",
    "slo_error_budget_remaining",
    "slo_burn_rate",
    "slo_burn_alerts_total",
    "slo_wanted_replicas",
})

#: fleet-supervisor event kinds — the actuation vocabulary of
#: serve/supervisor.py (the control loop that closes the /scale
#: advisory: spawn/drain/hold decisions with the advisory inputs
#: that drove them, replica lifecycle transitions, dead-replica
#: replacement, and crash-recovery adoption).  Every decision lands
#: on the durable `<fleet>/supervisor_events.jsonl` stream so a
#: whole scaling episode replays from telemetry alone.  Enforced
#: BOTH directions by obs-coverage check 16 across supervisor.py +
#: router.py + jobledger.py.
SUPERVISOR_EVENTS = frozenset({
    "supervisor-start",
    "supervisor-stop",
    "supervisor-adopt",
    "supervisor-spawn",
    "supervisor-spawn-failed",
    "supervisor-up",
    "supervisor-drain",
    "supervisor-drained",
    "supervisor-drain-timeout",
    "supervisor-replace",
    "supervisor-hold",
    "supervisor-step-error",
})

#: fleet-supervisor span names (check 16, both directions): one span
#: per gated decision plus one per actuation, so a scaling episode's
#: trace mirrors its event stream
SUPERVISOR_SPANS = frozenset({
    "supervisor:decide",
    "supervisor:spawn",
    "supervisor:drain",
    "supervisor:replace",
})

#: fleet-supervisor metrics (check 16, both directions, subset of
#: METRICS): the supervised-fleet gauge and the actuation counters —
#: holds included, because withheld actuations are the hysteresis
#: doing its job and must be observable
SUPERVISOR_METRICS = frozenset({
    "supervisor_replicas",
    "supervisor_spawns_total",
    "supervisor_drains_total",
    "supervisor_replacements_total",
    "supervisor_holds_total",
})

#: campaign-engine event kinds — the archive-reprocessing vocabulary
#: of serve/campaign.py (bounded-wave admission, fence-checked
#: settling, backfill-yield throttle decisions) plus the
#: supervisor's paced preemption of campaign-leased replicas
#: (serve/supervisor.py).  Every decision lands on the durable
#: per-campaign `campaign_events.jsonl` stream so a whole campaign —
#: including every preemption and every yield change — replays from
#: telemetry alone.  Enforced BOTH directions by obs-coverage check
#: 17 across campaign.py + router.py + supervisor.py.
CAMPAIGN_EVENTS = frozenset({
    "campaign-create",
    "campaign-resume",
    "campaign-wave-admit",
    "campaign-obs-done",
    "campaign-obs-failed",
    "campaign-yield",
    "campaign-preempt",
    "campaign-complete",
})

#: campaign-engine span names (check 17, both directions, subset of
#: SERVE_SPANS): creation, the driver pulse, each idempotent DAG
#: admission, and each supervisor preemption
CAMPAIGN_SPANS = frozenset({
    "campaign:create",
    "campaign:pulse",
    "campaign:admit",
    "campaign:preempt",
})

#: campaign-engine metrics (check 17, both directions, subset of
#: METRICS): wave/admission/settle counters, the outstanding-DAG
#: bound, the live backfill-yield factor, and the supervisor's
#: preemption pacer
CAMPAIGN_METRICS = frozenset({
    "campaign_waves_total",
    "campaign_admitted_total",
    "campaign_settled_total",
    "campaign_outstanding",
    "campaign_yield_factor",
    "campaign_preemptions_total",
})

#: federation event kinds — the many-fleets-behind-one-front-door
#: vocabulary of serve/federation.py: fleet membership and liveness
#: (the `LeaseLedger` core re-bound a third time, after DM shards and
#: beams — now the *hosts* are whole fleets), priced placement,
#: saturation spill-over, and the whole-fleet failover protocol
#: (dead-fleet detection, re-admission of its uncommitted work on
#: survivors, and the epoch fence that rejects a zombie fleet's late
#: commit).  Enforced BOTH directions by obs-coverage check 19
#: against serve/federation.py — the cross-site recovery path may
#: neither go dark nor go stale.
FED_EVENTS = frozenset({
    "fed-fleet-join",
    "fed-admit",
    "fed-place",
    "fed-commit",
    "fed-readmit",
    "fed-stale-commit",
    "fed-fleet-dead",
    "fed-epoch-bump",
    "fed-spill",
    "fed-push-error",
    "fed-probe-error",
    "fed-chaos-point",
})

#: federation span names (check 19, both directions, subset of
#: SERVE_SPANS): the front door's admission spans, each priced
#: placement decision, and each whole-fleet failover pass
FED_SPANS = frozenset({
    "fed:submit",
    "fed:dag-submit",
    "fed:place",
    "fed:failover",
})

#: federation metrics (check 19, both directions, subset of METRICS):
#: the liveness gauge pair plus admission/spill/failover counters —
#: the one-level-up mirror of the fleet_* recovery counters
FED_METRICS = frozenset({
    "fed_fleets_alive",
    "fed_epoch",
    "fed_submissions_total",
    "fed_spills_total",
    "fed_readmits_total",
    "fed_stale_commits_total",
    "fed_commits_total",
})

#: federation chaos kill points — the seams serve/federation.py fires
#: through its FaultInjector hook (`self._point(...)`); the runtime
#: copy is serve/federation.FED_KILL_POINTS (re-exported by
#: testing/chaos.py) and check 19 pins all three copies to each other
FED_KILL_POINTS = frozenset({
    "fleet-dead",
    "pre-readmit",
    "post-readmit",
    "zombie-fleet-commit",
})

#: learned-triage event kinds — the score-then-fold vocabulary of
#: presto_tpu/triage + the serve/dag.py triage node: a learned
#: selection ("triage-score"), the heuristic degrade when the weights
#: file is missing/corrupt/stale ("triage-fallback" — the poisoned-
#: model row of ROBUSTNESS.md), and each calibration run
#: ("triage-calibrate").  Enforced BOTH directions by obs-coverage
#: check 20 across presto_tpu/triage/ + serve/dag.py: the selection
#: path that decides which candidates are never folded may neither go
#: dark nor go stale.
TRIAGE_EVENTS = frozenset({
    "triage-score",
    "triage-fallback",
    "triage-calibrate",
})

#: learned-triage span names (check 20, both directions, subset of
#: SERVE_SPANS): the DAG triage node's score+fan-out transaction
TRIAGE_SPANS = frozenset({
    "serve:triage-node",
})

#: learned-triage metrics (check 20, both directions, subset of
#: METRICS): scored/avoided counters plus the recall gauge fed by
#: injection ground-truth sidecars when traffic carries them
TRIAGE_METRICS = frozenset({
    "triage_candidates_scored_total",
    "triage_folds_avoided_total",
    "triage_recall",
})

#: streaming-layer event kinds — every `events.emit("<kind>", ...)`
#: in presto_tpu/stream/ (enforced both directions by obs_lint check
#: 7: the live trigger path may not emit unregistered kinds, and the
#: catalog may not list dead ones)
STREAM_EVENTS = frozenset({
    "stream-start",
    "stream-eof",
    "stream-drop",
    "stream-quarantine",
    "trigger",
    "beam-start",
    "beam-stall",
    "beam-drop",
    "beam-veto",
    "beam-eof",
    "beam-handoff",
})

#: beam-multiplexer event kinds (stream/beams.py): the assembler's
#: per-beam lifecycle plus the beam ledger's EV_* flight-recorder
#: kinds (lease/fence transitions for beam hand-off across replicas).
#: The emit-style kinds are a subset of STREAM_EVENTS (check 7 covers
#: the stream tree); check 18 pins the full set — including the EV_*
#: attributes check 7's EMIT_RE cannot see — both directions against
#: stream/beams.py, so the hand-off audit trail may neither go dark
#: nor go stale.
BEAM_EVENTS = frozenset({
    "beam-start",
    "beam-stall",
    "beam-drop",
    "beam-veto",
    "beam-eof",
    "beam-handoff",
    "beam-lease",
    "beam-done",
    "beam-redo",
    "beam-stale-write",
    "beam-replica-dead",
    "beam-epoch-bump",
})

#: streaming-layer span names — every `obs.span("stream:...")` in
#: presto_tpu/stream/ (both directions, like TUNE_SPANS)
STREAM_SPANS = frozenset({
    "stream:block",
    "stream:dedisp",
    "stream:search",
    "stream:beam-tick",
})

#: beam-multiplexer span names (subset of STREAM_SPANS; check 18 pins
#: the subset relation and both directions against stream/beams.py)
BEAM_SPANS = frozenset({
    "stream:beam-tick",
})

#: beam-multiplexer metric names (subset of METRICS; check 18 pins
#: both directions against stream/beams.py): the live-beam gauge and
#: the per-beam QoS/veto/hand-off counters
BEAM_METRICS = frozenset({
    "stream_beams",
    "stream_beam_stalled_total",
    "stream_beam_dropped_total",
    "stream_beam_vetoed_total",
    "stream_beam_handoffs_total",
})

#: beam-multiplexer chaos kill points — the seams stream/beams.py
#: fires through its FaultInjector hook (`self._point(...)`); the
#: runtime copy is stream/beams.BEAM_KILL_POINTS (re-exported by
#: testing/chaos.py) and check 18 pins all three copies to each other
BEAM_KILL_POINTS = frozenset({
    "beam-tick",
    "beam-commit",
    "beam-handoff",
})

#: serve-layer span names — every `obs.span("...")` in
#: presto_tpu/serve/ (enforced both directions by obs_lint check 11:
#: the scheduler's per-job execution span and the stacked batch
#: executor's cross-job span may neither go dark nor go stale)
SERVE_SPANS = frozenset({
    "serve-job",
    "serve:stacked-batch",
    "serve:dag-node",
    "fleet:submit",
    "fleet:dag-submit",
    "slo:evaluate",
    "supervisor:decide",
    "supervisor:spawn",
    "supervisor:drain",
    "supervisor:replace",
    "campaign:create",
    "campaign:pulse",
    "campaign:admit",
    "campaign:preempt",
    "fed:submit",
    "fed:dag-submit",
    "fed:place",
    "fed:failover",
    "serve:triage-node",
})

#: discovery-DAG event kinds — the dependency-aware job-graph
#: vocabulary of serve/dag.py + serve/jobledger.py (graph admission,
#: the sift node's fenced fan-out transaction, cascade failure of a
#: failed parent's subtree).  Enforced BOTH directions by obs_lint
#: check 12: the DAG recovery path (the code that runs while a
#: mid-graph replica dies) may neither go dark nor go stale.
DAG_EVENTS = frozenset({
    "dag-submit",
    "dag-expand",
    "dag-cascade-fail",
})

#: discovery-DAG span names (subset of SERVE_SPANS; check 12 pins the
#: subset relation and both directions against serve/dag.py)
DAG_SPANS = frozenset({
    "serve:dag-node",
})

#: discovery-DAG metrics — every `dag_*` name must be registered by
#: the DAG layer (serve/dag.py, serve/jobledger.py, serve/router.py)
#: and vice versa (obs_lint check 12, both directions)
DAG_METRICS = frozenset({
    "dag_submitted_total",
    "dag_fanout_jobs_total",
    "dag_cascade_failures_total",
    "dag_nodes_done_total",
    "dag_folds_stacked_total",
})

#: kernel-observatory span names — the AOT cost-probe / roofline
#: microbench span opened by obs/costmodel.py + obs/roofline.py
#: (enforced both directions by obs-coverage check 15: every
#: `obs:`-prefixed span in the cost layer is registered, and the
#: catalog may not list dead ones)
COST_SPANS = frozenset({
    "obs:roofline-probe",
})

#: kernel-observatory metrics (obs-coverage check 15, both
#: directions, subset of METRICS): the per-kind FLOP/byte dispatch
#: join and the degradation counter — the measurement rig every
#: remaining perf item (Pallas dedisp, GPU backend, learned tuner)
#: is judged by, so it may neither go dark nor go stale
COST_METRICS = frozenset({
    "kernel_flops_total",
    "kernel_hbm_bytes_total",
    "cost_model_unavailable",
})

#: job lifecycle states -> the event kind that announces the
#: transition into that state.  The linter checks each mapped kind is
#: actually emitted somewhere in the serve layer.
JOB_STATE_EVENTS = {
    "queued": "enqueue",
    "scheduled": "schedule",
    "running": "execute",
    "retry-wait": "retry",
    "parked": "park",
    "done": "complete",
    "failed": "fail",
    "timeout": "fail",
}

#: tuning-layer span names — every `obs.span("tune:...")` in
#: presto_tpu/tune/ + apps/tune.py (the linter enforces both
#: directions, like the kill points)
TUNE_SPANS = frozenset({
    "tune:family",
    "tune:sweep",
    "tune:candidate",
})

#: fused-pipeline span names — every `obs.span("pipeline:...")` in
#: pipeline/fusion.py (enforced both directions by obs_lint check 8:
#: the in-memory data path may not open unregistered spans, and the
#: catalog may not list dead ones)
FUSION_SPANS = frozenset({
    "pipeline:seam",
    "pipeline:shard-seam",
})

#: the DM-sharded subset of the fused-pipeline vocabulary (obs_lint
#: check 9 pins all three sets BOTH directions: the sharded seam is
#: the one data path that holds an entire survey's fan-out across
#: devices with nothing durable on disk until spill, so its spans,
#: kill points, and metrics may neither go dark nor go stale)
SHARDED_FUSION_SPANS = frozenset({
    "pipeline:shard-seam",
})

SHARDED_KILL_POINTS = frozenset({
    "shard-seam-handoff",
    "sharded-fused-chunk",
})

SHARDED_FUSION_METRICS = frozenset({
    "survey_fused_shard_trials_total",
    "survey_fused_shard_gather_bytes_total",
})

#: fleet-serving metrics — every `fleet_*` name must be registered by
#: the fleet modules (serve/jobledger.py, serve/fleet.py,
#: serve/router.py) and vice versa (obs_lint check 10, both
#: directions, the same pinning discipline as the sharded seam: a
#: replica-loss recovery path may neither go dark nor go stale)
FLEET_METRICS = frozenset({
    "fleet_jobs_leased_total",
    "fleet_jobs_committed_total",
    "fleet_jobs_redone_total",
    "fleet_jobs_failed_total",
    "fleet_stale_results_total",
    "fleet_inflight",
    "fleet_epoch",
    "fleet_submissions_total",
    "fleet_shed_total",
    "fleet_quota_rejections_total",
    "fleet_depth",
    "fleet_replicas_ready",
    "fleet_batch_leases_total",
    "fleet_idle_tune_total",
    "fleet_obs_snapshots_total",
    "fleet_obs_aggregations_total",
})

#: registered metric names (Prometheus side of the contract); the
#: linter checks every registry.counter/gauge/histogram call in the
#: tree registers a name listed here.
METRICS = frozenset({
    # serve scheduler / queue
    "serve_jobs_done_total",
    "serve_jobs_failed_total",
    "serve_job_retries_total",
    "serve_batches_total",
    "serve_batched_jobs_total",
    "serve_batch_degrades_total",
    "serve_device_errors_total",
    "serve_retry_waiting",
    "serve_queue_depth",
    "serve_queue_capacity",
    "serve_uptime_seconds",
    "serve_jobs",
    "serve_jobs_parked_total",
    # stacked cross-job batch executor (serve/batchexec.py)
    "serve_stacked_batches_total",
    "serve_stacked_jobs_total",
    "serve_batch_occupancy",
    # plan cache (incl. the persistent tier, serve/plancache.PlanStore)
    "plancache_hits_total",
    "plancache_misses_total",
    "plancache_evictions_total",
    "plancache_size",
    "plancache_warm_fraction",
    "plancache_prewarmed_total",
    "plancache_store_plans",
    # latency / stage timing
    "latency_seconds",
    "survey_stage_seconds",
    # ingest quality
    "ingest_scrubbed_samples_total",
    "ingest_quarantined_spectra_total",
    "ingest_reports_total",
    # jax compile/device telemetry
    "jax_compiles_total",
    "jax_compile_seconds",
    "jax_dispatches_total",
    "jax_device_put_bytes_total",
    "jax_device_get_bytes_total",
    "jax_donated_bytes_total",
    "jax_live_buffer_bytes",
    "jax_live_buffer_hwm_bytes",
    # kernel observatory (obs/costmodel.py + obs/roofline.py +
    # bench.py); pinned both directions by obs-coverage check 15 via
    # COST_METRICS
    "kernel_flops_total",
    "kernel_hbm_bytes_total",
    "cost_model_unavailable",
    # flight recorder
    "flightrec_dumps_total",
    # elastic cluster (parallel/elastic.py)
    "cluster_epoch",
    "cluster_alive_hosts",
    "cluster_shards_done_total",
    "cluster_shard_redos_total",
    "cluster_epoch_bumps_total",
    "cluster_barrier_timeouts_total",
    "cluster_stale_writes_total",
    "cluster_heartbeats_total",
    # kernel autotuning (presto_tpu/tune); every tune_* name here must
    # be registered by the tune layer (obs_lint check 6)
    "tune_db_hits_total",
    "tune_db_misses_total",
    "tune_db_load_errors_total",
    "tune_db_entries",
    "tune_candidates_total",
    "tune_candidates_pruned_total",
    "tune_candidates_quarantined_total",
    "tune_sweep_seconds",
    # scheduler lanes (serve/scheduler.py)
    "serve_lane_batches_total",
    # device-resident pipeline fusion (pipeline/fusion.py); every
    # survey_fused_* name here must be registered by the fusion layer
    # (obs_lint check 8)
    "survey_fused_trials_total",
    "survey_fused_bytes_spilled_total",
    # DM-sharded seam (pipeline/fusion.ShardedSeamBlock); pinned both
    # directions by obs_lint check 9 via SHARDED_FUSION_METRICS
    "survey_fused_shard_trials_total",
    "survey_fused_shard_gather_bytes_total",
    # fleet serving (serve/fleet.py + jobledger.py + router.py);
    # pinned both directions by obs_lint check 10 via FLEET_METRICS
    "fleet_jobs_leased_total",
    "fleet_jobs_committed_total",
    "fleet_jobs_redone_total",
    "fleet_jobs_failed_total",
    "fleet_stale_results_total",
    "fleet_inflight",
    "fleet_epoch",
    "fleet_submissions_total",
    "fleet_shed_total",
    "fleet_quota_rejections_total",
    "fleet_depth",
    "fleet_replicas_ready",
    "fleet_batch_leases_total",
    "fleet_idle_tune_total",
    # fleet-wide observability (serve/fleet.py snapshot publisher,
    # serve/router.py aggregation endpoint, the admit->lease-wait->
    # execute->commit decomposition); pinned both directions by
    # obs_lint check 13 via FLEET_OBS_METRICS
    "fleet_obs_snapshots_total",
    "fleet_obs_aggregations_total",
    "job_e2e_seconds",
    # SLO observatory (serve/jobledger.py usage metering +
    # serve/router.py budget/burn/scale signals); pinned both
    # directions by obs_lint check 14 via SLO_METRICS
    "slo_device_seconds_total",
    "slo_error_budget_remaining",
    "slo_burn_rate",
    "slo_burn_alerts_total",
    "slo_wanted_replicas",
    # fleet supervisor (serve/supervisor.py actuation loop); pinned
    # both directions by obs-coverage check 16 via SUPERVISOR_METRICS
    "supervisor_replicas",
    "supervisor_spawns_total",
    "supervisor_drains_total",
    "supervisor_replacements_total",
    "supervisor_holds_total",
    # campaign engine (serve/campaign.py driver + the supervisor's
    # preempt-fraction pacer); pinned both directions by obs-coverage
    # check 17 via CAMPAIGN_METRICS
    "campaign_waves_total",
    "campaign_admitted_total",
    "campaign_settled_total",
    "campaign_outstanding",
    "campaign_yield_factor",
    "campaign_preemptions_total",
    # federation front door (serve/federation.py); pinned both
    # directions by obs-coverage check 19 via FED_METRICS
    "fed_fleets_alive",
    "fed_epoch",
    "fed_submissions_total",
    "fed_spills_total",
    "fed_readmits_total",
    "fed_stale_commits_total",
    "fed_commits_total",
    # streaming search (presto_tpu/stream); every stream_* name here
    # must be registered by the stream layer (obs_lint check 7)
    "stream_blocks_total",
    "stream_candidates_total",
    "stream_triggers_total",
    "stream_drops_total",
    "stream_gap_spectra_total",
    "stream_backlog_blocks",
    "stream_latency_seconds",
    # beam multiplexer (stream/beams.py); pinned both directions by
    # obs_lint check 18 via BEAM_METRICS
    "stream_beams",
    "stream_beam_stalled_total",
    "stream_beam_dropped_total",
    "stream_beam_vetoed_total",
    "stream_beam_handoffs_total",
    # discovery DAGs (serve/dag.py + jobledger.py + router.py);
    # pinned both directions by obs_lint check 12 via DAG_METRICS
    "dag_submitted_total",
    "dag_fanout_jobs_total",
    "dag_cascade_failures_total",
    "dag_nodes_done_total",
    "dag_folds_stacked_total",
    # learned candidate triage (presto_tpu/triage + the serve/dag.py
    # triage node); pinned both directions by obs-coverage check 20
    # via TRIAGE_METRICS
    "triage_candidates_scored_total",
    "triage_folds_avoided_total",
    "triage_recall",
})
