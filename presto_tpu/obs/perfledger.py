"""Fingerprint-keyed, append-only performance ledger (obs layer).

Committed bench artifacts (BENCH_r*.json) pin point-in-time numbers;
nothing watched the *trajectory*.  The perf ledger is the durable
time series a regression gate can judge against:

    {"schema": 1,
     "episodes": [
       {"run_id": "...", "ts": 1754...,
        "fingerprint": "<tune/db.py device fingerprint>",
        "workload": "smoke" | "full",
        "source": "bench.py" | "perf-gate",
        "metrics": {
          "<name>": {"median": 1.2e9, "mad": 3.1e7, "k": 5,
                     "unit": "cells/s", "direction": "higher"}}}]}

Rules (the tune/db.py durability discipline):

  * episodes are median-of-k with the median absolute deviation kept
    as the per-episode noise band — the gate's tolerance scales with
    the measurement's own jitter, not a guessed constant;
  * the fingerprint is the comparability boundary: a baseline is only
    ever computed over episodes with the SAME fingerprint + workload
    (a CPU episode never gates a TPU run);
  * appends are merge-appends: re-read disk, union by ``run_id``,
    atomic replace — concurrent bench runs compose;
  * loads are defensive: corruption/stale schema degrades to an empty
    ledger with ``load_error`` set and a warning, never a crash (the
    gate then FAILS with a usable message rather than crashing CI).

The gate itself (``gate()``, CLI ``tools/perf_gate.py``) compares the
newest episode against the rolling baseline — the median of the
previous ``window`` same-fingerprint episodes per metric — and flags a
regression when the direction-adjusted delta exceeds
``max(rel_tol * baseline, mad_k * noise)``.
"""

from __future__ import annotations

import json
import os
import time
import uuid
import warnings
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

#: env override for the ledger location (CLI --ledger wins over this)
ENV_LEDGER = "PRESTO_TPU_PERF_LEDGER"

#: the repo root this package is installed in (three levels up) —
#: where the committed PERF_LEDGER.json lives
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def default_ledger_path() -> str:
    env = os.environ.get(ENV_LEDGER, "")
    if env:
        return env
    return os.path.join(REPO, "PERF_LEDGER.json")


# ----------------------------------------------------------------------
# statistics
# ----------------------------------------------------------------------

def median(xs) -> float:
    s = sorted(float(x) for x in xs)
    n = len(s)
    if not n:
        raise ValueError("median of nothing")
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def mad(xs) -> float:
    """Median absolute deviation — the robust noise band a couple of
    outlier reps cannot inflate."""
    m = median(xs)
    return median(abs(float(x) - m) for x in xs)


def metric_from_samples(samples, unit: str,
                        direction: str = "higher") -> dict:
    """One episode metric from raw per-rep samples."""
    if direction not in ("higher", "lower"):
        raise ValueError("direction must be 'higher' or 'lower'")
    return {"median": median(samples), "mad": mad(samples),
            "k": len(list(samples)), "unit": unit,
            "direction": direction}


def make_episode(metrics: Dict[str, dict],
                 fingerprint: Optional[str] = None,
                 workload: str = "full",
                 source: str = "bench.py",
                 run_id: Optional[str] = None,
                 meta: Optional[dict] = None) -> dict:
    if fingerprint is None:
        from presto_tpu.tune.db import fingerprint_key
        fingerprint = fingerprint_key()
    ep = {
        "run_id": run_id or uuid.uuid4().hex[:12],
        "ts": time.time(),
        "fingerprint": fingerprint,
        "workload": workload,
        "source": source,
        "metrics": {str(k): dict(v) for k, v in metrics.items()},
    }
    if meta:
        ep["meta"] = dict(meta)
    return ep


def _valid_episode(ep) -> bool:
    return (isinstance(ep, dict) and isinstance(ep.get("run_id"), str)
            and isinstance(ep.get("metrics"), dict)
            and isinstance(ep.get("ts"), (int, float)))


# ----------------------------------------------------------------------
# the ledger
# ----------------------------------------------------------------------

class PerfLedger:
    """In-memory view of PERF_LEDGER.json (episodes sorted by ts;
    ``load_error`` records why a file on disk was unusable)."""

    def __init__(self, episodes: Optional[List[dict]] = None,
                 load_error: Optional[str] = None):
        self.episodes: List[dict] = list(episodes or [])
        self.load_error = load_error

    @classmethod
    def load(cls, path: str) -> "PerfLedger":
        """Defensive load: any structural problem degrades to an
        EMPTY ledger with ``load_error`` set and a warning — a bad
        ledger must never take a bench run down (the gate turns
        ``load_error`` into an explicit failure instead)."""
        if not os.path.exists(path):
            return cls()
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError) as e:
            warnings.warn(
                "perf ledger %s is unreadable (%s) — starting empty"
                % (path, e), RuntimeWarning, stacklevel=2)
            return cls(load_error="unreadable: %s" % e)
        if not isinstance(raw, dict) or \
                raw.get("schema") != SCHEMA_VERSION:
            got = raw.get("schema") if isinstance(raw, dict) else None
            warnings.warn(
                "perf ledger %s has schema %r (want %d) — starting "
                "empty" % (path, got, SCHEMA_VERSION),
                RuntimeWarning, stacklevel=2)
            return cls(load_error="stale schema: %r" % (got,))
        eps = raw.get("episodes")
        if not isinstance(eps, list):
            warnings.warn(
                "perf ledger %s has a malformed episodes list — "
                "starting empty" % path, RuntimeWarning, stacklevel=2)
            return cls(load_error="malformed episodes")
        good = [ep for ep in eps if _valid_episode(ep)]
        led = cls(episodes=good)
        led.episodes.sort(key=lambda e: e["ts"])
        return led

    def merge(self, other: "PerfLedger") -> None:
        """Append-only union by run_id (ts-sorted afterwards) — two
        concurrent writers both land, nothing is ever rewritten."""
        seen = {ep["run_id"] for ep in self.episodes}
        for ep in other.episodes:
            if _valid_episode(ep) and ep["run_id"] not in seen:
                self.episodes.append(ep)
                seen.add(ep["run_id"])
        self.episodes.sort(key=lambda e: e["ts"])

    def append(self, episode: dict) -> None:
        if not _valid_episode(episode):
            raise ValueError("malformed episode")
        self.merge(PerfLedger(episodes=[episode]))

    def save(self, path: str) -> None:
        """Merge-save: fold in whatever is on disk now, then replace
        atomically."""
        from presto_tpu.io.atomic import atomic_write_text
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        on_disk = PerfLedger.load(path)
        merged = PerfLedger(episodes=list(on_disk.episodes))
        merged.merge(self)
        atomic_write_text(path, json.dumps(
            {"schema": SCHEMA_VERSION, "episodes": merged.episodes},
            indent=1, sort_keys=True) + "\n")
        self.episodes = merged.episodes

    # -- selection -----------------------------------------------------

    def select(self, fingerprint: Optional[str] = None,
               workload: Optional[str] = None) -> List[dict]:
        out = []
        for ep in self.episodes:
            if fingerprint is not None and \
                    ep.get("fingerprint") != fingerprint:
                continue
            if workload is not None and \
                    ep.get("workload") != workload:
                continue
            out.append(ep)
        return out


# ----------------------------------------------------------------------
# the regression gate
# ----------------------------------------------------------------------

def rolling_baseline(history: List[dict], metric: str,
                     window: int = 5) -> Optional[dict]:
    """Baseline for one metric over the last ``window`` episodes of
    an already-selected (same fingerprint + workload) history:
    median-of-medians plus the widest recent noise band."""
    rows = [ep["metrics"][metric] for ep in history[-window:]
            if metric in ep.get("metrics", {})]
    rows = [r for r in rows
            if isinstance(r.get("median"), (int, float))]
    if not rows:
        return None
    return {
        "median": median(r["median"] for r in rows),
        "mad": max(float(r.get("mad", 0.0) or 0.0) for r in rows),
        "n": len(rows),
        "unit": rows[-1].get("unit", ""),
        "direction": rows[-1].get("direction", "higher"),
    }


def gate(episode: dict, history: List[dict], window: int = 5,
         rel_tol: float = 0.15, mad_k: float = 4.0) -> dict:
    """Judge ``episode`` against the rolling baseline of ``history``
    (same-fingerprint episodes, EXCLUDING the episode itself).

    A metric regresses when its direction-adjusted delta is worse
    than ``max(rel_tol * |baseline|, mad_k * noise)`` where noise is
    the larger of the baseline's and the episode's MAD bands.
    Returns {"ok": bool, "rows": [...]} with one row per judged
    metric (metrics with no baseline yet are "no-baseline", never a
    failure — the first episodes seed the ledger)."""
    rows = []
    ok = True
    prior = [ep for ep in history
             if ep.get("run_id") != episode.get("run_id")]
    for name, m in sorted(episode.get("metrics", {}).items()):
        value = m.get("median")
        if not isinstance(value, (int, float)):
            continue
        base = rolling_baseline(prior, name, window=window)
        if base is None:
            rows.append({"metric": name, "status": "no-baseline",
                         "value": value, "unit": m.get("unit", "")})
            continue
        direction = m.get("direction", base["direction"])
        noise = max(float(m.get("mad", 0.0) or 0.0), base["mad"])
        threshold = max(rel_tol * abs(base["median"]), mad_k * noise)
        delta = (base["median"] - value if direction == "higher"
                 else value - base["median"])     # >0 == worse
        status = "regression" if delta > threshold else "ok"
        if status == "regression":
            ok = False
        rows.append({
            "metric": name, "status": status,
            "value": value, "baseline": base["median"],
            "delta_worse": delta, "threshold": threshold,
            "noise_band": noise, "baseline_n": base["n"],
            "direction": direction, "unit": m.get("unit", ""),
        })
    return {"ok": ok, "rows": rows}


def inject_slowdown(episode: dict, factor: float) -> dict:
    """A synthetic degraded copy of ``episode`` (rates divided /
    times multiplied by ``factor``) — the deliberate-slowdown proof
    that the gate actually trips (tools/perf_gate.py
    --inject-slowdown, tests/test_perfledger.py)."""
    if factor <= 1.0:
        raise ValueError("slowdown factor must be > 1")
    out = json.loads(json.dumps(episode))
    out["run_id"] = "inject-" + uuid.uuid4().hex[:8]
    out["source"] = "inject-slowdown"
    for m in out.get("metrics", {}).values():
        if not isinstance(m.get("median"), (int, float)):
            continue
        if m.get("direction", "higher") == "higher":
            m["median"] = m["median"] / factor
        else:
            m["median"] = m["median"] * factor
    return out
