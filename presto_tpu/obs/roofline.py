"""Device roofline: measured peaks + compute/memory-bound placement.

The roofline model needs two device constants — peak FLOP/s and peak
memory bandwidth — to place a kernel by its operational intensity
(FLOPs per HBM byte): below the ridge point
``peak_flops / peak_bandwidth`` a kernel is memory-bound, above it
compute-bound.  This module measures both ONCE per device with a
microbenchmark (a dominant-term matmul for FLOP/s, a streaming triad
for bytes/s) and caches them in the tune fingerprint DB
(``tune/db.py``, family ``device_roofline``) — the same
cache-correctness boundary tuning results use, so a GPU or a new TPU
generation gets its own peaks automatically and the whole cost stack
inherits multi-backend support for free (docs/TUNING.md).

Both halves degrade: no usable backend -> ``device_peaks`` returns
None and every consumer renders "(no peaks)" instead of a verdict;
the classification itself is pure arithmetic (unit-tested without a
device).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

#: tune-DB family holding the cached peaks per device fingerprint
FAMILY = "device_roofline"

#: shape key under the family (versioned: a methodology change bumps
#: it, orphaning stale peak records instead of silently mixing them)
SHAPE_KEY = "peaks_v1"


# ----------------------------------------------------------------------
# the microbench
# ----------------------------------------------------------------------

def measure_peaks(obs=None, reps: int = 3, n_mm: int = 1024,
                  n_bw: int = 1 << 24) -> Dict[str, float]:
    """Measure (peak FLOP/s, peak bytes/s) on the default backend.

    * FLOP/s: an [n, n] @ [n, n] float32 matmul (2*n^3 FLOPs, the
      highest-intensity program XLA will emit — its rate is the
      practical FLOP ceiling);
    * bytes/s: a fused streaming reduce ``sum(a*s + b)`` over n_bw
      float32 elements (2 full arrays read -> 8*n_bw bytes per run,
      intensity ~0.25 FLOP/byte — far below any ridge, so its rate is
      the practical bandwidth ceiling; the reduce keeps XLA from
      eliding any element).

    Best-of-``reps`` for both (the peak is a ceiling, not an
    average).  Raises when no backend is usable — callers cache via
    ``device_peaks`` which degrades to None.
    """
    import jax
    import jax.numpy as jnp
    sp = (obs.span("obs:roofline-probe", op="peaks", n_mm=n_mm,
                   n_bw=n_bw)
          if obs is not None and obs.enabled else None)
    try:
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, (n_mm, n_mm), dtype=jnp.float32)
        mm = jax.jit(lambda x: (x @ x).sum())
        float(mm(a))                              # compile + settle
        mm_s = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            float(mm(a))
            mm_s = min(mm_s, time.perf_counter() - t0)
        flops_per_s = 2.0 * n_mm ** 3 / mm_s

        x = jax.random.normal(key, (n_bw,), dtype=jnp.float32)
        y = jax.random.normal(jax.random.PRNGKey(1), (n_bw,),
                              dtype=jnp.float32)
        triad = jax.jit(lambda a, b: (a * 1.0001 + b).sum())
        float(triad(x, y))                        # compile + settle
        bw_s = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            float(triad(x, y))
            bw_s = min(bw_s, time.perf_counter() - t0)
        bytes_per_s = 8.0 * n_bw / bw_s           # two full reads
    except BaseException as e:
        if sp is not None:
            sp.finish("error: %s" % type(e).__name__)
        raise
    if sp is not None:
        sp.finish()
    return {
        "flops_per_s": flops_per_s,
        "bytes_per_s": bytes_per_s,
        "ridge_intensity": flops_per_s / bytes_per_s,
        "matmul_s": mm_s,
        "triad_s": bw_s,
        "n_mm": float(n_mm),
        "n_bw": float(n_bw),
        "measured_at": time.time(),
    }


# ----------------------------------------------------------------------
# fingerprint-cached access
# ----------------------------------------------------------------------

def device_peaks(obs=None, db_path: Optional[str] = None,
                 measure: bool = True,
                 reps: int = 3) -> Optional[Dict[str, float]]:
    """Peaks for the CURRENT device fingerprint, off the tune DB when
    already measured; with ``measure=True`` a miss runs the microbench
    once and merge-saves the result (keep-the-best on the matmul wall
    time, so concurrent measurers keep the fastest = highest ceiling).
    Returns None when nothing is cached and measurement is off or
    impossible — consumers degrade to "(no peaks)"."""
    from presto_tpu.tune.db import TuneDB, default_db_path, \
        fingerprint_key
    try:
        fp = fingerprint_key()
    except Exception:
        return None
    path = db_path or default_db_path()
    db = TuneDB.load(path)
    rec = db.lookup(fp, FAMILY, SHAPE_KEY)
    if rec is not None:
        return dict(rec)
    if not measure:
        return None
    try:
        peaks = measure_peaks(obs=obs, reps=reps)
    except Exception:
        return None
    db.record(fp, FAMILY, SHAPE_KEY, peaks,
              median_s=float(peaks["matmul_s"]), reps=reps,
              source="roofline")
    try:
        db.save(path)
    except OSError:
        pass                      # read-only cache dir: still usable
    return peaks


# ----------------------------------------------------------------------
# classification (pure arithmetic; unit-tested without a device)
# ----------------------------------------------------------------------

def classify(flops: float, hbm_bytes: float,
             peaks: Dict[str, float]) -> Optional[dict]:
    """Place one kernel on the roofline.  Returns None when the cost
    or the peaks are unusable (zero bytes, missing fields)."""
    try:
        pf = float(peaks["flops_per_s"])
        pb = float(peaks["bytes_per_s"])
    except (KeyError, TypeError, ValueError):
        return None
    if hbm_bytes <= 0 or pf <= 0 or pb <= 0:
        return None
    intensity = float(flops) / float(hbm_bytes)
    ridge = pf / pb
    # the roofline: attainable FLOP/s = min(peak, intensity * bw)
    attainable = min(pf, intensity * pb)
    return {
        "intensity": intensity,
        "ridge_intensity": ridge,
        "bound": "compute" if intensity >= ridge else "memory",
        "attainable_flops_per_s": attainable,
        "frac_of_peak_flops": attainable / pf,
    }


def roofline_rows(costs: dict,
                  peaks: Optional[Dict[str, float]]) -> list:
    """Per-kind roofline rows for a kernel_costs snapshot (the
    presto-report table): every kind with a harvested unit gets an
    intensity + verdict (or "(no peaks)"), every kind that only
    dispatched gets an explicit "(unavailable)" row, and each row
    carries its share of the total attributed HBM traffic."""
    kinds = (costs or {}).get("kinds", {}) or {}
    total_bytes = sum(float(e.get("hbm_bytes_total", 0.0) or 0.0)
                      for e in kinds.values())
    rows = []
    for kind, ent in sorted(kinds.items()):
        flops = ent.get("flops_per_dispatch")
        nbytes = ent.get("hbm_bytes_per_dispatch")
        row = {
            "kind": kind,
            "dispatches": int(ent.get("dispatches", 0)),
            "flops_per_dispatch": flops,
            "hbm_bytes_per_dispatch": nbytes,
            "flops_total": ent.get("flops_total", 0.0),
            "hbm_bytes_total": ent.get("hbm_bytes_total", 0.0),
            "hbm_share": (float(ent.get("hbm_bytes_total", 0.0) or
                                0.0) / total_bytes
                          if total_bytes > 0 else 0.0),
            "peak_bytes": ent.get("peak_bytes"),
        }
        if flops is None or nbytes is None:
            row["verdict"] = "(unavailable)"
        elif peaks is None:
            row["intensity"] = (flops / nbytes if nbytes else None)
            row["verdict"] = "(no peaks)"
        else:
            cls = classify(flops, nbytes, peaks)
            if cls is None:
                row["verdict"] = "(no peaks)"
            else:
                row.update(cls)
                row["verdict"] = "%s-bound" % cls["bound"]
        rows.append(row)
    return rows
