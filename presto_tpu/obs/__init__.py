"""presto_tpu.obs — unified tracing, metrics, and flight recorder.

The cross-cutting observability layer: one metrics registry
(obs/metrics.py), one structured tracer (obs/trace.py), one flight
recorder (obs/flightrec.py), and the JAX compile/device telemetry
helpers (obs/jaxtel.py), bundled by :class:`Observability` so every
subsystem threads a single handle instead of five dialects of ad-hoc
accounting.

Cost contract: everything is off-by-default-cheap.  A disabled
Observability answers every record call with one branch, and a survey
run without observability is byte-identical to an uninstrumented one
(no telemetry files are ever written while disabled).

Enabling it:

  * the serve layer is always observed (a resident service without
    /metrics is blind) — `SearchService` builds an enabled handle;
  * batch surveys opt in via ``SurveyConfig.obs`` (an ObsConfig or an
    Observability) or process-wide with ``PRESTO_TPU_OBS=1``.

See docs/OBSERVABILITY.md for the metric catalog, span taxonomy, and
flight-recorder triage guide.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional

from presto_tpu.obs.metrics import MetricsRegistry
from presto_tpu.obs.flightrec import FlightRecorder, find_dumps
from presto_tpu.obs.trace import (NOOP_SPAN, SpanContext, Tracer,
                                  chrome_trace, write_chrome_trace)

__all__ = [
    "ObsConfig", "Observability", "get_obs", "configure",
    "resolve_obs", "MetricsRegistry", "Tracer", "SpanContext",
    "FlightRecorder", "find_dumps", "chrome_trace",
    "write_chrome_trace", "NOOP_SPAN",
]

#: environment switch: PRESTO_TPU_OBS=1 enables the process default
ENV_SWITCH = "PRESTO_TPU_OBS"


@dataclass
class ObsConfig:
    """Observability knobs (wire-safe: plain values only)."""
    enabled: bool = False
    #: directory for spans.jsonl + trace.perfetto.json; None defers to
    #: the survey workdir (flush(default_dir=...)) or disables export
    trace_dir: Optional[str] = None
    #: flight-recorder ring capacity (records)
    flightrec_capacity: int = 2048
    #: logical service name stamped on dumps/reports
    service: str = "presto_tpu"

    @classmethod
    def from_env(cls) -> "ObsConfig":
        on = os.environ.get(ENV_SWITCH, "") not in ("", "0")
        return cls(enabled=on,
                   trace_dir=os.environ.get(ENV_SWITCH + "_DIR")
                   or None)


class Observability:
    """One handle bundling registry + tracer + flight recorder."""

    def __init__(self, cfg: Optional[ObsConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.cfg = cfg or ObsConfig()
        self.enabled = bool(self.cfg.enabled)
        self.metrics = registry if registry is not None else \
            MetricsRegistry(enabled=self.enabled)
        self.flightrec = FlightRecorder(
            capacity=self.cfg.flightrec_capacity,
            enabled=self.enabled)
        jsonl = (os.path.join(self.cfg.trace_dir, "spans.jsonl")
                 if self.cfg.trace_dir else None)
        self.tracer = Tracer(enabled=self.enabled, jsonl_path=jsonl,
                             on_finish=self.flightrec.note_span)

    # -- convenience fronts -------------------------------------------
    def span(self, name: str, parent=None, **attrs):
        """Start a span (no-op singleton when disabled)."""
        if not self.enabled:
            return NOOP_SPAN
        return self.tracer.span(name, parent=parent, **attrs)

    def event(self, kind: str, **fields) -> None:
        """Record a discrete event into the flight recorder."""
        if not self.enabled:
            return
        self.flightrec.add(kind, **fields)

    def dump_flight(self, workdir: str, reason: str) -> Optional[str]:
        """Post-mortem: dump ring + open spans + metrics snapshot.
        Never raises."""
        if not self.enabled:
            return None
        try:
            path = self.flightrec.dump(
                workdir, reason,
                open_spans=self.tracer.open_spans(),
                metrics=self.metrics.snapshot())
        except Exception:
            return None
        if path is not None:
            self.metrics.counter(
                "flightrec_dumps_total",
                "Flight-recorder post-mortem dumps",
                ("reason",)).labels(reason=reason).inc()
        return path

    def flush(self, default_dir: Optional[str] = None) -> None:
        """Export buffered spans as a Perfetto/Chrome trace into
        cfg.trace_dir (or `default_dir`).  Safe to call repeatedly;
        never raises."""
        if not self.enabled:
            return
        d = self.cfg.trace_dir or default_dir
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            spans = self.tracer.finished()
            if spans:
                write_chrome_trace(
                    os.path.join(d, "trace.perfetto.json"), spans)
                if self.tracer._jsonl_path is None:
                    # no streaming sink configured: snapshot the span
                    # buffer so presto-report still has spans.jsonl
                    import json as _json
                    from presto_tpu.io.atomic import atomic_write_text
                    atomic_write_text(
                        os.path.join(d, "spans.jsonl"),
                        "".join(_json.dumps(s.to_json(),
                                            sort_keys=True) + "\n"
                                for s in spans))
            # kernel-cost book -> kernel_costs.json (the roofline
            # section presto-report renders); no-op when nothing was
            # harvested, never runs device work
            from presto_tpu.obs import costmodel
            costmodel.write_costs(self, d)
        except Exception:
            pass


# ----------------------------------------------------------------------
# process-wide default handle
# ----------------------------------------------------------------------

_default: Optional[Observability] = None
_default_lock = threading.Lock()


def get_obs() -> Observability:
    """The process default Observability (enabled iff
    PRESTO_TPU_OBS=1 at first use, or after configure())."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Observability(ObsConfig.from_env())
    return _default


def configure(cfg: ObsConfig) -> Observability:
    """Replace the process default (tests, app entry points)."""
    global _default
    with _default_lock:
        _default = Observability(cfg)
    return _default


def resolve_obs(obj) -> Observability:
    """Normalize a SurveyConfig-style ``obs`` field: None -> the
    process default, ObsConfig -> a fresh handle, Observability ->
    itself."""
    if obj is None:
        return get_obs()
    if isinstance(obj, Observability):
        return obj
    if isinstance(obj, ObsConfig):
        return Observability(obj)
    raise TypeError("obs must be ObsConfig or Observability, not %r"
                    % type(obj).__name__)
