"""Flight recorder: the last N seconds of telemetry, dumped on death.

A bounded ring buffer collects recent telemetry records — finished
spans (wired in by ``Observability``), discrete events (chaos kill
points, scheduler transitions, log lines), whatever a component
chooses to note.  On an unhandled exception, a typed
``PrestoIOError``, or an injected chaos ``SimulatedCrash``, the ring
is dumped atomically (io/atomic.py — a crash during the dump cannot
leave a torn file) to ``<workdir>/flightrec-<ts>.json``, so every
post-mortem starts with what the process was actually doing when it
died instead of a bare traceback.

The dump carries three sections:

  * ``records``   — the ring, oldest first (events + finished spans);
  * ``open_spans``— spans started but unfinished at dump time (the
                    call stack of the death, in span form);
  * ``metrics``   — a registry snapshot, when one is attached.

Recording while disabled costs one branch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from presto_tpu.io.atomic import atomic_write_text

DUMP_PREFIX = "flightrec-"


class FlightRecorder:
    """Thread-safe bounded telemetry ring + atomic post-mortem dump."""

    def __init__(self, capacity: int = 2048, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._dumps = 0

    # -- recording ----------------------------------------------------
    def add(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "ts": time.time(), "kind": kind}
            rec.update(fields)
            self._ring.append(rec)

    def note_span(self, span) -> None:
        """Tracer on_finish hook: finished spans enter the ring."""
        if not self.enabled:
            return
        self.add("span", name=span.name, span_id=span.span_id,
                 parent_id=span.parent_id, trace_id=span.trace_id,
                 duration_s=round(span.duration, 6),
                 status=span.status, thread=span.thread,
                 attrs=dict(span.attrs))

    # -- inspection ---------------------------------------------------
    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def last(self, kind: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            ring = list(self._ring)
        for rec in reversed(ring):
            if kind is None or rec["kind"] == kind:
                return rec
        return None

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    # -- post-mortem --------------------------------------------------
    def dump(self, workdir: str, reason: str,
             open_spans: Optional[List] = None,
             metrics: Optional[dict] = None) -> Optional[str]:
        """Atomically write the ring to
        ``<workdir>/flightrec-<stamp>.json``; returns the path (None
        when disabled).  Never raises — a failing dump must not mask
        the exception that triggered it."""
        if not self.enabled:
            return None
        now = time.time()
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
        path = os.path.join(
            workdir, "%s%s-%06d.json"
            % (DUMP_PREFIX, stamp, int((now % 1.0) * 1e6)))
        payload = {
            "version": 1,
            "reason": reason,
            "ts": now,
            "pid": os.getpid(),
            "records": self.records(),
            "open_spans": [s.to_json() for s in (open_spans or [])],
        }
        if metrics is not None:
            payload["metrics"] = metrics
        try:
            os.makedirs(workdir, exist_ok=True)
            atomic_write_text(path, json.dumps(payload, indent=1,
                                               sort_keys=True) + "\n")
        except OSError:
            return None
        with self._lock:
            self._dumps += 1
        return path


def find_dumps(workdir: str) -> List[str]:
    """All flight-recorder dumps in a workdir, oldest first."""
    try:
        names = os.listdir(workdir)
    except OSError:
        return []
    return sorted(os.path.join(workdir, n) for n in names
                  if n.startswith(DUMP_PREFIX) and n.endswith(".json"))
