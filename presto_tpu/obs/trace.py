"""Structured tracing: nested spans with contextvar propagation.

A span is one timed operation with a name, attributes, and an
identity (trace_id / span_id / parent_id).  The current span rides a
``contextvars.ContextVar``, so nesting is automatic within a thread:
a serve job's root span threads through scheduler -> plan cache ->
search kernels, and a survey run's spans nest stage -> chunk -> op
without any explicit plumbing.

Threads do NOT inherit context; code that fans work out to workers
captures ``tracer.context()`` (a SpanContext) and passes it as the
``parent=`` of spans started on the worker — the same shape OpenTelemetry
uses for cross-thread propagation.

Finished spans are buffered (bounded), optionally streamed to a JSONL
file (one span per line, append-only), and exportable as Chrome/
Perfetto ``trace_event`` JSON (``write_chrome_trace``) so presto_tpu
traces sit next to the PRESTO_TPU_PROFILE JAX traces in the same
viewer.

A disabled tracer costs one branch: ``span()`` returns a shared no-op
singleton and records nothing.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

from presto_tpu.io.atomic import atomic_write_text


def _new_id(nhex: int) -> str:
    return uuid.uuid4().hex[:nhex]


class SpanContext:
    """Portable span identity for cross-thread / cross-process
    parenting.  `to_dict`/`from_dict` are the wire form the fleet
    uses to propagate the context through ledger JSON: the router
    stamps it onto the admitted job row, the leasing replica resumes
    it as the explicit `parent=` of the job's root span, so one
    discovery DAG renders as ONE trace even when every node ran on a
    different replica (docs/OBSERVABILITY.md, "Fleet observability")."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d) -> "Optional[SpanContext]":
        """None for anything that is not a usable wire context (a
        row without a trace field, a disabled-tracer stamp)."""
        if not isinstance(d, dict) or not d.get("trace_id"):
            return None
        return cls(str(d["trace_id"]), str(d.get("span_id") or ""))

    def __repr__(self):
        return "SpanContext(%s, %s)" % (self.trace_id, self.span_id)


class Span:
    """One live (or finished) span.  Usable as a context manager or
    finished manually with .finish()."""

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attrs: Dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.start = time.time()
        self.end = 0.0
        self.status = "ok"
        self.thread = threading.current_thread().name
        self._token: Optional[contextvars.Token] = None

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def set_attr(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def finish(self, status: str = "ok") -> None:
        self._tracer._finish(self, status)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, etype, exc, tb) -> None:
        self.finish("error: %s" % etype.__name__ if etype else "ok")

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration, 6),
            "status": self.status,
            "thread": self.thread,
            "pid": os.getpid(),
            "attrs": self.attrs,
        }


class _NoopSpan:
    """Shared do-nothing span for the disabled path (one allocation,
    ever)."""

    name = ""
    trace_id = span_id = parent_id = None
    attrs: Dict = {}
    status = "ok"
    duration = 0.0

    def set_attr(self, key, value):
        return self

    def context(self):
        return None

    def finish(self, status: str = "ok"):
        pass

    def __enter__(self):
        return self

    def __exit__(self, etype, exc, tb):
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory + finished-span buffer + optional JSONL sink."""

    def __init__(self, enabled: bool = True, keep: int = 8192,
                 jsonl_path: Optional[str] = None, on_finish=None):
        self.enabled = enabled
        self._cv: contextvars.ContextVar = contextvars.ContextVar(
            "presto_tpu_span", default=None)
        self._lock = threading.Lock()  # presto-lint: guards(_finished, _open, _jsonl_fh)
        self._finished: deque = deque(maxlen=keep)
        self._open: Dict[str, Span] = {}
        self._on_finish = on_finish
        self._jsonl_path = jsonl_path
        self._jsonl_fh = None

    # -- span lifecycle -----------------------------------------------
    def span(self, name: str, parent=None, current: bool = True,
             **attrs):
        """Start a span (sets it current for this context unless
        ``current=False`` — sibling spans opened in bulk, e.g. the
        per-job spans of a stacked batch, must not nest into each
        other).  `parent` may be a Span or SpanContext for explicit
        (e.g. cross-thread or cross-process) parenting; default is
        the context's current span."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = self._cv.get()
        if parent is None:
            trace_id, parent_id = _new_id(32), None
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        sp = Span(self, name, trace_id, _new_id(16), parent_id, attrs)
        if current:
            sp._token = self._cv.set(sp)
        with self._lock:
            self._open[sp.span_id] = sp
        return sp

    def _finish(self, span: Span, status: str) -> None:
        if span.end:                     # idempotent double-finish
            return
        span.end = time.time()
        span.status = status
        if span._token is not None:
            try:
                self._cv.reset(span._token)
            except ValueError:
                # finished from a different context (cross-thread
                # hand-off); current-span restoration is moot there
                pass
            span._token = None
        with self._lock:
            self._open.pop(span.span_id, None)
            self._finished.append(span)
            fh = self._ensure_jsonl()
            if fh is not None:
                fh.write(json.dumps(span.to_json(), sort_keys=True)
                         + "\n")
                fh.flush()
        if self._on_finish is not None:
            self._on_finish(span)

    # -- context ------------------------------------------------------
    def current(self) -> Optional[Span]:
        return self._cv.get()

    def context(self) -> Optional[SpanContext]:
        """Capture the current span's identity for another thread."""
        sp = self._cv.get()
        return None if sp is None else sp.context()

    # -- inspection / export ------------------------------------------
    def finished(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def open_spans(self) -> List[Span]:
        """Started-but-unfinished spans (what a flight-recorder dump
        wants to show about the moment of death)."""
        with self._lock:
            return sorted(self._open.values(), key=lambda s: s.start)

    def attach_jsonl(self, path: str) -> bool:
        """Late-bind a JSONL streaming sink (the fleet replica wires
        its spans into `<fleet>/obs/<replica>.spans.jsonl` here).
        A sink configured at construction (e.g. `-tracedir`) wins —
        returns False and leaves it untouched."""
        if not self.enabled:
            return False
        with self._lock:
            if self._jsonl_path is not None:
                return False
            self._jsonl_path = path
            return True

    def _ensure_jsonl(self):  # presto-lint: holds(_lock)
        if self._jsonl_path is None:
            return None
        if self._jsonl_fh is None:
            d = os.path.dirname(os.path.abspath(self._jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._jsonl_fh = open(self._jsonl_path, "a")
        return self._jsonl_fh

    def close(self) -> None:
        with self._lock:
            if self._jsonl_fh is not None:
                self._jsonl_fh.close()
                self._jsonl_fh = None


# ----------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ----------------------------------------------------------------------

def chrome_trace(spans: List[Span]) -> dict:
    """Spans -> Chrome ``trace_event`` JSON (complete 'X' events),
    loadable in Perfetto / chrome://tracing alongside the JAX profiler
    traces PRESTO_TPU_PROFILE captures."""
    tids: Dict[str, int] = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s.thread, len(tids) + 1)
        events.append({
            "name": s.name,
            "cat": "presto_tpu",
            "ph": "X",
            "ts": s.start * 1e6,
            "dur": max(s.end - s.start, 0.0) * 1e6,
            "pid": os.getpid(),
            "tid": tid,
            "args": dict(s.attrs, trace_id=s.trace_id,
                         span_id=s.span_id,
                         parent_id=s.parent_id or "",
                         status=s.status),
        })
    events += [{"name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid, "args": {"name": tname}}
               for tname, tid in tids.items()]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: List[Span]) -> str:
    atomic_write_text(path, json.dumps(chrome_trace(spans)) + "\n")
    return path
