"""SLO observatory: per-tenant error budgets, multi-window burn
rates, and the advisory `/scale` signal.

The fleet has had *measurements* since PR 12 (`job_e2e_seconds`
through `obs/fleetagg.py`); this module turns them into *decision
signals* — the serving-economics layer of the ROADMAP control-plane
item.  Everything here is a pure function over the durable usage
ledger (`serve/usage.py`: one row per fence-checked terminal job),
so the signals survive replica death and router restarts and can be
recomputed byte-for-byte from telemetry alone.

**Specs** (`SloSpec`) are declarative, one per tenant: an
availability objective (fraction of terminal jobs that must be
*good*) and an optional per-job latency objective (a done job slower
than `latency_s` end-to-end counts as bad — the deadline-lane analog
at fleet scope).  Specs persist as `<fleet>/slo.json` so the router,
the fleet report, and a future supervisor all read one source of
truth.

**Error budget**: with objective ``o``, the budget fraction is
``1 - o``; over the ledger's lifetime, ``budget_used = bad_fraction
/ (1 - o)`` (1.0 = budget exactly spent).

**Burn rates** follow the multi-window multi-burn-rate pattern from
the Google SRE workbook: ``burn(window) = bad_fraction(window) /
(1 - o)`` — burn 1 spends the budget exactly at the objective's
natural rate; burn N spends it N× faster.  An alert pair (fast
window, slow window, threshold) fires only when BOTH windows exceed
the threshold: the fast window gives reaction time, the slow window
suppresses blips.  Defaults are the classic 5m/1h @ 14.4 (page) and
30m/6h @ 6 (ticket) pairs.

**Window algebra**: burn evaluation factors through `window_state` —
pure per-window good/bad counts — and `merge_states`, which is
associative and commutative; for ANY partition of the usage rows
into shards, ``burn(merge(states(shards))) == burn(state(all
rows))``.  tests/test_slo.py property-tests this over random shard
splits, mirroring the fleetagg percentile proof, so burn rates can
be computed incrementally or federated without drift.

**Scale advisory**: `scale_advice` derives a wanted-replica count
from the ledger backlog *expressed in expected device-seconds* (the
per-bucket mean `execute` phase is the cost model, exactly as the
ROADMAP frames predictive admission) divided by per-replica measured
capacity (device-seconds actually executed per wall-second in a
recent window), targeting a configurable drain time; active burn
alerts add pressure (one replica above current ready).  The advisory
is just that — this PR derives and exposes the signal; acting on it
(an autoscaler, device-seconds admission) is the remaining
control-plane follow-up.

See docs/OBSERVABILITY.md, "SLO observatory".
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from presto_tpu.io.atomic import atomic_write_text

SPEC_NAME = "slo.json"

SPEC_VERSION = 1

#: Google-SRE-workbook default alert pairs:
#: (fast window s, slow window s, burn threshold)
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 3600.0, 14.4),
    (1800.0, 21600.0, 6.0),
)

#: sparkline glyphs for the report's burn history
_SPARK = "▁▂▃▄▅▆▇█"


@dataclass(frozen=True)
class BurnWindow:
    """One fast/slow alert pair with its burn-rate threshold."""
    fast_s: float
    slow_s: float
    threshold: float

    @property
    def key(self) -> str:
        return "%gs/%gs" % (self.fast_s, self.slow_s)


@dataclass
class SloSpec:
    """One tenant's declarative service-level objective."""
    tenant: str
    objective: float                    # availability target in (0,1)
    latency_s: Optional[float] = None   # per-job e2e latency objective
    windows: Tuple[BurnWindow, ...] = tuple(
        BurnWindow(*w) for w in DEFAULT_WINDOWS)

    @property
    def budget_frac(self) -> float:
        return max(1.0 - float(self.objective), 1e-9)

    def to_dict(self) -> dict:
        return {"tenant": self.tenant,
                "objective": self.objective,
                "latency_s": self.latency_s,
                "windows": [[w.fast_s, w.slow_s, w.threshold]
                            for w in self.windows]}

    @classmethod
    def from_dict(cls, d: dict) -> "SloSpec":
        windows = tuple(BurnWindow(float(f), float(s), float(t))
                        for f, s, t in (d.get("windows")
                                        or DEFAULT_WINDOWS))
        lat = d.get("latency_s")
        return cls(tenant=str(d["tenant"]),
                   objective=float(d["objective"]),
                   latency_s=None if lat is None else float(lat),
                   windows=windows)


def parse_spec(text: str,
               windows: Optional[Sequence[Tuple[float, float,
                                                float]]] = None) \
        -> SloSpec:
    """One CLI spec string ``tenant:objective[:latency_s]`` (the
    router's ``-slo`` flag)."""
    parts = text.split(":")
    if len(parts) < 2:
        raise ValueError(
            "SLO spec %r must be tenant:objective[:latency_s]"
            % text)
    objective = float(parts[1])
    if not 0.0 < objective < 1.0:
        raise ValueError("SLO objective %r must be in (0, 1)"
                         % parts[1])
    kw = {}
    if windows:
        kw["windows"] = tuple(BurnWindow(*w) for w in windows)
    return SloSpec(tenant=parts[0], objective=objective,
                   latency_s=float(parts[2]) if len(parts) > 2
                   else None, **kw)


def parse_windows(text: str) -> Optional[List[Tuple[float, float,
                                                    float]]]:
    """``fast:slow:threshold[,fast:slow:threshold...]`` -> window
    tuples (None for an empty string: keep the defaults)."""
    text = (text or "").strip()
    if not text:
        return None
    out = []
    for part in text.split(","):
        f, s, t = (float(x) for x in part.split(":"))
        out.append((f, s, t))
    return out


def spec_path(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), SPEC_NAME)


def save_specs(fleetdir: str, specs: Sequence[SloSpec]) -> str:
    """Persist the spec set atomically as `<fleet>/slo.json` — the
    one source of truth the router, report, and future supervisor
    share."""
    path = spec_path(fleetdir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_write_text(path, json.dumps(
        {"version": SPEC_VERSION,
         "specs": [s.to_dict() for s in specs]},
        indent=1, sort_keys=True) + "\n")
    return path


def load_specs(fleetdir: str) -> List[SloSpec]:
    """The persisted spec set ([] when absent/unreadable — SLO
    evaluation simply has nothing to say then, never fails)."""
    try:
        with open(spec_path(fleetdir)) as f:
            doc = json.load(f)
        if int(doc.get("version", -1)) != SPEC_VERSION:
            return []
        return [SloSpec.from_dict(d) for d in doc.get("specs") or []]
    except (OSError, ValueError, KeyError, TypeError):
        return []


# ----------------------------------------------------------------------
# event classification + window algebra
# ----------------------------------------------------------------------

def classify(spec: SloSpec, row: dict) -> bool:
    """True when the usage row is a *good* event under this spec: a
    committed job within the latency objective.  Terminal failures
    and over-latency completions spend budget."""
    if row.get("state") != "done":
        return False
    if spec.latency_s is not None:
        total = float((row.get("phases") or {}).get("total") or 0.0)
        if total > spec.latency_s:
            return False
    return True


def window_state(spec: SloSpec, rows: Iterable[dict],
                 now: float) -> dict:
    """Pure per-window good/bad counts for one tenant — the
    mergeable \"registry\" burn evaluation factors through.  An event
    is in window W iff ``now - ts <= W``."""
    lengths = sorted({w.fast_s for w in spec.windows}
                     | {w.slow_s for w in spec.windows})
    state = {
        "tenant": spec.tenant,
        "total": 0,
        "bad": 0,
        "windows": {"%g" % length: {"good": 0, "bad": 0}
                    for length in lengths},
    }
    for row in rows:
        if str(row.get("tenant") or "") != spec.tenant:
            continue
        good = classify(spec, row)
        state["total"] += 1
        if not good:
            state["bad"] += 1
        age = now - float(row.get("ts") or 0.0)
        for length in lengths:
            if age <= length:
                key = "good" if good else "bad"
                state["windows"]["%g" % length][key] += 1
    return state


def merge_states(a: dict, b: dict) -> dict:
    """Sum two window states (associative + commutative — the window
    algebra the property test pins: merged-window burn equals the
    single-registry computation)."""
    out = {"tenant": a.get("tenant") or b.get("tenant"),
           "total": int(a.get("total", 0)) + int(b.get("total", 0)),
           "bad": int(a.get("bad", 0)) + int(b.get("bad", 0)),
           "windows": {}}
    keys = set(a.get("windows") or {}) | set(b.get("windows") or {})
    for k in sorted(keys):
        wa = (a.get("windows") or {}).get(k, {})
        wb = (b.get("windows") or {}).get(k, {})
        out["windows"][k] = {
            "good": int(wa.get("good", 0)) + int(wb.get("good", 0)),
            "bad": int(wa.get("bad", 0)) + int(wb.get("bad", 0)),
        }
    return out


def _burn(counts: dict, budget_frac: float) -> Tuple[float, int]:
    """(burn rate, events) for one window's counts: bad fraction over
    the budget fraction.  No events -> burn 0 (an idle tenant spends
    nothing)."""
    n = int(counts.get("good", 0)) + int(counts.get("bad", 0))
    if n == 0:
        return 0.0, 0
    return (counts.get("bad", 0) / n) / budget_frac, n


def evaluate_state(spec: SloSpec, state: dict) -> dict:
    """Burn-rate + budget evaluation over a (possibly merged) window
    state.  Deterministic: same state, same answer."""
    windows = []
    alert = False
    for w in spec.windows:
        fast, nf = _burn(state["windows"]["%g" % w.fast_s],
                         spec.budget_frac)
        slow, ns = _burn(state["windows"]["%g" % w.slow_s],
                         spec.budget_frac)
        alerting = (nf > 0 and ns > 0 and fast >= w.threshold
                    and slow >= w.threshold)
        alert = alert or alerting
        windows.append({
            "window": w.key,
            "fast_s": w.fast_s,
            "slow_s": w.slow_s,
            "threshold": w.threshold,
            "fast_burn": round(fast, 4),
            "slow_burn": round(slow, 4),
            "fast_events": nf,
            "slow_events": ns,
            "alerting": alerting,
        })
    total, bad = int(state["total"]), int(state["bad"])
    used = ((bad / total) / spec.budget_frac) if total else 0.0
    return {
        "tenant": spec.tenant,
        "objective": spec.objective,
        "latency_s": spec.latency_s,
        "events": total,
        "good": total - bad,
        "bad": bad,
        "budget_frac": round(spec.budget_frac, 9),
        "budget_used": round(used, 4),
        "budget_remaining": round(max(1.0 - used, 0.0), 4),
        "windows": windows,
        "alert": alert,
    }


def evaluate(spec: SloSpec, rows: Iterable[dict],
             now: float) -> dict:
    """One tenant's full SLO view straight from usage rows."""
    return evaluate_state(spec, window_state(spec, rows, now))


def burn_series(spec: SloSpec, rows: Sequence[dict], now: float,
                window_s: float, step_s: float,
                n: int = 16) -> List[float]:
    """Trailing burn-rate history: burn over `window_s` evaluated at
    ``n`` instants ending at `now`, `step_s` apart (the report's
    sparkline input)."""
    mine = [r for r in rows
            if str(r.get("tenant") or "") == spec.tenant]
    out = []
    for i in range(n):
        t = now - (n - 1 - i) * step_s
        counts = {"good": 0, "bad": 0}
        for row in mine:
            ts = float(row.get("ts") or 0.0)
            if 0.0 <= t - ts <= window_s:
                counts["good" if classify(spec, row) else "bad"] += 1
        out.append(round(_burn(counts, spec.budget_frac)[0], 4))
    return out


def sparkline(values: Sequence[float]) -> str:
    """Max-scaled unicode sparkline ('' for no data)."""
    if not values:
        return ""
    top = max(values)
    if top <= 0:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int(v / top * (len(_SPARK) - 1) + 0.5))]
        for v in values)


# ----------------------------------------------------------------------
# the backfill lane (campaign traffic yields to interactive burn)
# ----------------------------------------------------------------------

BACKFILL_NAME = "backfill.json"

BACKFILL_VERSION = 1

#: default lowest fraction of its configured WRR weight a backfill
#: tenant keeps while an interactive tenant is burning hard — the
#: campaign never fully starves (it would otherwise never finish),
#: it just slows to a trickle
BACKFILL_FLOOR = 0.05


def backfill_path(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), BACKFILL_NAME)


def save_backfill(fleetdir: str, tenants: Sequence[str],
                  yield_factor: float = 1.0,
                  floor: float = BACKFILL_FLOOR) -> str:
    """Durably declare the backfill tenant set (atomic, versioned —
    the campaign driver writes this once at start; the live
    ``yield`` field is then maintained by update_backfill_yield)."""
    path = backfill_path(fleetdir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_write_text(path, json.dumps(
        {"version": BACKFILL_VERSION,
         "tenants": sorted(str(t) for t in tenants),
         "floor": float(floor),
         "yield": float(yield_factor)},
        indent=1, sort_keys=True) + "\n")
    return path


def load_backfill(fleetdir: str) -> Optional[dict]:
    """The backfill declaration (None when absent/unreadable — no
    backfill lane, nothing yields)."""
    try:
        with open(backfill_path(fleetdir)) as f:
            doc = json.load(f)
        if int(doc.get("version", -1)) != BACKFILL_VERSION:
            return None
        return doc
    except (OSError, ValueError, TypeError):
        return None


def backfill_yield_factor(evals: Dict[str, dict],
                          exclude: Iterable[str] = (),
                          floor: float = BACKFILL_FLOOR) -> float:
    """The backfill-yield rule, a pure function over per-tenant SLO
    evaluations: while every interactive tenant burns its error
    budget at <= 1x (the sustainable rate), backfill keeps its full
    configured weight (factor 1.0); past that the factor shrinks as
    ``1 / worst_burn`` — a gold tenant burning 14x shrinks the
    campaign lane 14x — floored so the campaign never fully starves.
    ``exclude`` names the backfill tenants themselves (their own
    burn must not throttle them)."""
    excl = set(exclude)
    worst = 0.0
    for tenant, ev in (evals or {}).items():
        if tenant in excl:
            continue
        for w in ev.get("windows") or ():
            if int(w.get("fast_events", 0)) > 0:
                worst = max(worst, float(w.get("fast_burn", 0.0)))
    if worst <= 1.0:
        return 1.0
    return max(min(floor, 1.0), 1.0 / worst)


def update_backfill_yield(fleetdir: str,
                          evals: Dict[str, dict]) -> Optional[float]:
    """Recompute the live yield factor from interactive burn and
    persist it (atomically) when it moved: the job ledger's lease
    policy stat-caches `backfill.json`, so the write IS the
    actuation.  Returns the factor, or None when no backfill lane is
    declared.  Callers (the router's SLO pass, the campaign driver's
    pulse) emit their own events on change."""
    doc = load_backfill(fleetdir)
    if doc is None:
        return None
    factor = backfill_yield_factor(
        evals, exclude=doc.get("tenants") or (),
        floor=float(doc.get("floor", BACKFILL_FLOOR)))
    if abs(factor - float(doc.get("yield", 1.0))) > 1e-9:
        save_backfill(fleetdir, doc.get("tenants") or (),
                      yield_factor=factor,
                      floor=float(doc.get("floor", BACKFILL_FLOOR)))
    return factor


# ----------------------------------------------------------------------
# usage rollups (device-seconds accounting)
# ----------------------------------------------------------------------

def _execute_s(row: dict) -> float:
    return float((row.get("phases") or {}).get("execute") or 0.0)


def usage_rollup(rows: Iterable[dict]) -> dict:
    """Per-tenant (and per-bucket) device-seconds rollup over usage
    rows.  Only committed (`done`) rows meter device-seconds — they
    are the rows whose `execute` phase also reached the
    `job_e2e_seconds` histogram, which is what makes the conservation
    property exact."""
    tenants: Dict[str, dict] = {}
    total_s = 0.0
    total_jobs = 0
    for row in rows:
        t = str(row.get("tenant") or "")
        ent = tenants.setdefault(t, {"device_seconds": 0.0,
                                     "jobs": 0, "failed": 0,
                                     "buckets": {}})
        if row.get("state") == "done":
            ex = _execute_s(row)
            ent["device_seconds"] += ex
            ent["jobs"] += 1
            total_s += ex
            total_jobs += 1
            b = str(row.get("bucket") or "")
            bent = ent["buckets"].setdefault(
                b, {"device_seconds": 0.0, "jobs": 0})
            bent["device_seconds"] += ex
            bent["jobs"] += 1
        else:
            ent["failed"] += 1
    for ent in tenants.values():
        ent["device_seconds"] = round(ent["device_seconds"], 6)
        for bent in ent["buckets"].values():
            bent["device_seconds"] = round(bent["device_seconds"], 6)
    return {"tenants": {t: tenants[t] for t in sorted(tenants)},
            "total_device_seconds": round(total_s, 6),
            "total_jobs": total_jobs}


def bucket_cost_model(rows: Iterable[dict]) -> Tuple[Dict[str, float],
                                                     Optional[float]]:
    """(per-bucket mean execute seconds, global mean) from committed
    usage rows — the expected-device-seconds cost model the scale
    advisory (and a future device-seconds admission gate) prices
    backlog with."""
    acc: Dict[str, List[float]] = {}
    all_ex: List[float] = []
    for row in rows:
        if row.get("state") != "done":
            continue
        ex = _execute_s(row)
        if ex <= 0.0:
            continue
        acc.setdefault(str(row.get("bucket") or ""), []).append(ex)
        all_ex.append(ex)
    means = {b: sum(xs) / len(xs) for b, xs in acc.items()}
    return means, (sum(all_ex) / len(all_ex)) if all_ex else None


def fleet_median_cost(means: Dict[str, float],
                      default_s: float = 5.0) -> float:
    """The fleet-median per-bucket cost — the estimate an admission
    gate charges a bucket it has never executed.  Median, not mean:
    one pathological bucket must not poison every unknown job's
    price.  `default_s` is the cold-fleet fallback (no bucket has
    committed yet)."""
    if not means:
        return default_s
    ordered = sorted(means.values())
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def cost_estimator(rows: Iterable[dict], default_s: float = 5.0):
    """``bucket -> expected device-seconds`` closure over the usage
    ledger: known buckets price at their mean committed execute
    seconds, unknown buckets at the fleet-median bucket cost (the
    AutoTVM-style measured-cost prior), and a cold fleet at
    `default_s`.  The closure is what `JobLedger.admit` charges
    device-second quotas with and what the router's device-second
    shedding prices backlog with — one model, every consumer."""
    means, _global_mean = bucket_cost_model(rows)
    fallback = fleet_median_cost(means, default_s)

    def estimate(bucket) -> float:
        return means.get(str(bucket or ""), fallback)

    estimate.buckets = len(means)      # type: ignore[attr-defined]
    estimate.fallback = fallback       # type: ignore[attr-defined]
    return estimate


# ----------------------------------------------------------------------
# the /scale advisory
# ----------------------------------------------------------------------

@dataclass
class ScaleConfig:
    """Knobs of the wanted-replica derivation."""
    target_drain_s: float = 30.0   # drain the backlog within this
    min_replicas: int = 1
    max_replicas: int = 16
    default_job_s: float = 5.0     # cost of a bucket never seen
    capacity_window_s: float = 300.0
    #: measured capacity clamp (device-seconds per wall-second per
    #: replica): a briefly idle fleet must not divide by ~zero
    min_capacity: float = 0.25
    max_capacity: float = 4.0


def measured_capacity(rows: Sequence[dict], now: float,
                      cfg: ScaleConfig, replicas: int) -> float:
    """Per-replica device-seconds executed per wall-second over the
    trailing capacity window (1.0 = one device fully busy).  Falls
    back to 1.0 with no recent commits — the cold-start assumption
    that one replica is one device."""
    recent = [r for r in rows
              if r.get("state") == "done"
              and now - float(r.get("ts") or 0.0)
              <= cfg.capacity_window_s]
    if not recent or replicas <= 0:
        return 1.0
    ex = sum(_execute_s(r) for r in recent)
    cap = ex / cfg.capacity_window_s / max(replicas, 1)
    return min(max(cap, cfg.min_capacity), cfg.max_capacity)


def scale_advice(backlog_buckets: Sequence[Optional[str]],
                 rows: Sequence[dict],
                 evals: Dict[str, dict],
                 ready_replicas: int,
                 cfg: Optional[ScaleConfig] = None,
                 now: float = 0.0,
                 campaign_remaining_s: float = 0.0) -> dict:
    """The advisory `/scale` signal: wanted replica count + reason.

    ``backlog_buckets`` is one entry per pending/leased ledger job
    (its bucket hint, None for unknown).  The backlog is priced in
    expected device-seconds via the per-bucket execute cost model,
    divided by per-replica measured capacity and the target drain
    time; tenants with an active burn alert add SLO-debt pressure
    (at least one replica above current ready).
    ``campaign_remaining_s`` is the running campaigns' projected
    remaining-archive device-seconds (`CampaignDriver.project`) —
    work the bounded-wave admission has not put in the ledger yet, so
    the count-based backlog cannot see it; folding it in lets a
    supervisor spin capacity up for an archive instead of chasing one
    wave at a time.  Pure function — a supervisor (or
    tools/fleet_chaos.py in reverse) can replay every decision from
    telemetry alone."""
    cfg = cfg or ScaleConfig()
    means, global_mean = bucket_cost_model(rows)
    fallback = global_mean if global_mean is not None \
        else cfg.default_job_s
    ledger_s = sum(means.get(str(b or ""), fallback)
                   for b in backlog_buckets)
    campaign_s = max(0.0, float(campaign_remaining_s))
    backlog_s = ledger_s + campaign_s
    capacity = measured_capacity(rows, now, cfg,
                                 max(ready_replicas, 1))
    demand = 0
    if backlog_buckets or campaign_s > 0.0:
        demand = int(math.ceil(
            backlog_s / (cfg.target_drain_s * capacity)))
    pressure = sorted(t for t, ev in (evals or {}).items()
                      if ev.get("alert"))
    wanted = demand
    if pressure:
        wanted = max(wanted, ready_replicas + 1)
    wanted = min(max(wanted, cfg.min_replicas), cfg.max_replicas)
    if pressure and wanted > demand:
        reason = ("slo-debt: %s burning error budget; "
                  "backlog %.1f device-s wants %d"
                  % (",".join(pressure), backlog_s, demand))
    elif backlog_buckets or campaign_s > 0.0:
        reason = ("backlog %.1f device-s (%.1f ledger + %.1f "
                  "campaign) / (%.0fs drain x %.2f cap/replica) "
                  "-> %d"
                  % (backlog_s, ledger_s, campaign_s,
                     cfg.target_drain_s, capacity, demand))
    else:
        reason = "idle: no backlog, no SLO pressure"
    return {
        "wanted_replicas": int(wanted),
        "reason": reason,
        "inputs": {
            "backlog_jobs": len(backlog_buckets),
            "backlog_device_seconds": round(backlog_s, 3),
            "campaign_remaining_device_seconds": round(
                campaign_s, 3),
            "per_replica_capacity": round(capacity, 4),
            "ready_replicas": int(ready_replicas),
            "target_drain_s": cfg.target_drain_s,
            "slo_pressure": pressure,
            "cost_model_buckets": len(means),
        },
    }
