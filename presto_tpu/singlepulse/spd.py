""".spd single-pulse diagnostic bundles (make_spd.py / spio analog).

The reference's make_spd.py saves a npz of everything the plot_spd
diagnostic needs: the dispersed and dedispersed waterfalls around the
candidate, the dedispersed time series, DM-vs-time context events, and
candidate metadata.  Same here — the .spd file IS a npz archive.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from presto_tpu.search.singlepulse import SPCandidate
from presto_tpu.singlepulse.waterfaller import waterfall


@dataclass
class SpdData:
    # candidate
    dm: float = 0.0
    sigma: float = 0.0
    time: float = 0.0
    downfact: int = 1
    dt: float = 0.0
    # cutouts (freq ascending)
    wf_raw: np.ndarray = field(default_factory=lambda: np.zeros((1, 1)))
    wf_dedisp: np.ndarray = field(
        default_factory=lambda: np.zeros((1, 1)))
    freqs: np.ndarray = field(default_factory=lambda: np.zeros(1))
    start_time: float = 0.0
    # dedispersed series around the pulse
    series: np.ndarray = field(default_factory=lambda: np.zeros(1))
    # DM-vs-time context (all events near the pulse)
    context_dm: np.ndarray = field(default_factory=lambda: np.zeros(0))
    context_time: np.ndarray = field(
        default_factory=lambda: np.zeros(0))
    context_sigma: np.ndarray = field(
        default_factory=lambda: np.zeros(0))
    source: str = ""


def make_spd(path: str, cand: SPCandidate, reader,
             context: Optional[Sequence[SPCandidate]] = None,
             window_sec: float = 0.2, nsub: int = 32,
             downsamp: int = 1) -> SpdData:
    """Build and save the .spd bundle for one candidate."""
    start = max(cand.time - window_sec / 2.0, 0.0)
    raw = waterfall(reader, start, window_sec, dm=0.0, nsub=nsub,
                    downsamp=downsamp)
    ded = waterfall(reader, start, window_sec, dm=cand.dm, nsub=nsub,
                    downsamp=downsamp)
    series = ded.data.sum(axis=0)
    context = list(context or [])
    spd = SpdData(
        dm=cand.dm, sigma=cand.sigma, time=cand.time,
        downfact=cand.downfact, dt=ded.dt,
        wf_raw=raw.data, wf_dedisp=ded.data, freqs=ded.freqs,
        start_time=ded.start_time, series=series,
        context_dm=np.array([c.dm for c in context]),
        context_time=np.array([c.time for c in context]),
        context_sigma=np.array([c.sigma for c in context]),
        source=getattr(reader.header, "source_name", ""))
    # write via a handle: np.savez would append ".npz" to a ".spd" path
    with open(path, "wb") as fh:
        _savez(fh, spd)
    return spd


def _savez(fh, spd: SpdData) -> None:
    np.savez_compressed(
        fh, dm=spd.dm, sigma=spd.sigma, time=spd.time,
        downfact=spd.downfact, dt=spd.dt, wf_raw=spd.wf_raw,
        wf_dedisp=spd.wf_dedisp, freqs=spd.freqs,
        start_time=spd.start_time, series=spd.series,
        context_dm=spd.context_dm, context_time=spd.context_time,
        context_sigma=spd.context_sigma, source=spd.source)


def read_spd(path: str) -> SpdData:
    with np.load(path, allow_pickle=False) as z:
        return SpdData(
            dm=float(z["dm"]), sigma=float(z["sigma"]),
            time=float(z["time"]), downfact=int(z["downfact"]),
            dt=float(z["dt"]), wf_raw=z["wf_raw"],
            wf_dedisp=z["wf_dedisp"], freqs=z["freqs"],
            start_time=float(z["start_time"]), series=z["series"],
            context_dm=z["context_dm"], context_time=z["context_time"],
            context_sigma=z["context_sigma"], source=str(z["source"]))
