"""Single-pulse diagnostic toolchain.

The reference ships this as lib/python/singlepulse/ (spcand.py, spio.py,
make_spd.py, plot_spd.py, rrattrap.py, bary_and_topo.py) plus
bin/waterfaller.py — grouping/rating of .singlepulse events across DM
trials (the "RRAT trap"), candidate cutout waterfalls, and the .spd
diagnostic bundle.  The search itself lives in
presto_tpu.search.singlepulse; this package is the downstream analysis.
"""

from presto_tpu.singlepulse.grouping import (SinglePulseGroup,
                                             group_candidates,
                                             rank_groups)
from presto_tpu.singlepulse.spd import SpdData, make_spd, read_spd
from presto_tpu.singlepulse.waterfaller import waterfall

__all__ = ["SinglePulseGroup", "group_candidates", "rank_groups",
           "waterfall", "SpdData", "make_spd", "read_spd"]
