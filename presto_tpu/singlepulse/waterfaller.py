"""Candidate cutout waterfalls (bin/waterfaller.py analog).

Extracts a [nsub, nsamp] dynamic-spectrum cutout around a single-pulse
candidate from a filterbank/PSRFITS reader, with optional subbanding,
time downsampling, and dedispersion at the candidate DM — the array
behind the reference's waterfall plots (plotting lives in
presto_tpu.plotting.spplot).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from presto_tpu.ops.dedispersion import dedisp_delays, delays_to_bins


@dataclass
class Waterfall:
    data: np.ndarray        # [nsub, nsamp] float32 (freq ascending)
    start_time: float       # seconds from obs start
    dt: float
    freqs: np.ndarray       # [nsub] center MHz, ascending
    dm: float


def waterfall(reader, start_sec: float, duration_sec: float,
              dm: float = 0.0, nsub: int = 0, downsamp: int = 1
              ) -> Waterfall:
    """Cut a waterfall out of `reader` (FilterbankFile/PsrfitsFile:
    needs .header-like metadata via hdr fields and read_spectra).

    Dedispersion shifts each channel EARLIER by its DM delay relative
    to the highest frequency, so a dispersed pulse lines up vertically;
    the read is extended by the full dispersion sweep so the cutout
    stays filled.
    """
    hdr = reader.header
    dt = hdr.tsamp
    nchan = hdr.nchans
    lof = hdr.lofreq             # center of lowest channel, MHz
    cw = abs(hdr.foff)
    delays = dedisp_delays(nchan, dm, lof, cw)
    delays = delays - delays.min()          # highest freq: zero delay
    dbins = np.asarray(delays_to_bins(delays, dt))
    sweep = int(dbins.max())

    start = max(int(start_sec / dt), 0)
    nsamp = int(np.ceil(duration_sec / dt))
    block = np.asarray(reader.read_spectra(start, nsamp + sweep)).T
    # block: [nchan, nsamp+sweep], ascending frequency; low channels
    # have the LARGEST delays
    out = np.empty((nchan, nsamp), np.float32)
    for c in range(nchan):
        out[c] = block[c, dbins[c]:dbins[c] + nsamp]

    if nsub and nsub < nchan:
        chans_per = nchan // nsub
        out = out[:nsub * chans_per].reshape(nsub, chans_per,
                                             nsamp).mean(axis=1)
        freqs = (lof + (np.arange(nsub) + 0.5) * chans_per * cw
                 - 0.5 * cw)
    else:
        freqs = lof + np.arange(nchan) * cw
    if downsamp > 1:
        keep = (out.shape[1] // downsamp) * downsamp
        out = out[:, :keep].reshape(out.shape[0], -1,
                                    downsamp).mean(axis=2)
        dt = dt * downsamp
    return Waterfall(data=out.astype(np.float32),
                     start_time=start * hdr.tsamp, dt=dt,
                     freqs=np.asarray(freqs, np.float64), dm=dm)
