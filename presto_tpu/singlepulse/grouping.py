"""Cross-DM grouping and rating of single-pulse events (rrattrap).

The reference's bin/rrattrap.py (823 LoC) groups .singlepulse events
that are close in (time, DM) and rates each group by the shape of its
sigma-vs-DM curve: real broadband single pulses peak in S/N at their
true DM and decay to either side, while RFI is strongest at DM~0 or
shows no DM structure.  Ranks follow the reference's ladder:

  1 noise     — too few members
  2 ungraded  — enough members, ambiguous DM structure
  3 ok        — S/N peaks away from the DM edges
  4 good      — clean rise-and-fall around a peak DM > min_dm
  5 excellent — good + strong peak (peak/edge S/N ratio > 1.3)
  6 awesome   — excellent + high absolute S/N

This is a behavioral re-implementation (same inputs, same artifact
columns, same rank semantics), not a line port.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.search.singlepulse import SPCandidate


@dataclass
class SinglePulseGroup:
    cands: List[SPCandidate] = field(default_factory=list)
    rank: int = 0

    @property
    def numcands(self) -> int:
        return len(self.cands)

    @property
    def min_dm(self) -> float:
        return min(c.dm for c in self.cands)

    @property
    def max_dm(self) -> float:
        return max(c.dm for c in self.cands)

    @property
    def max_sigma(self) -> float:
        return max(c.sigma for c in self.cands)

    @property
    def best_cand(self) -> SPCandidate:
        return max(self.cands, key=lambda c: c.sigma)

    @property
    def center_time(self) -> float:
        return float(np.median([c.time for c in self.cands]))

    @property
    def duration(self) -> float:
        ts = [c.time for c in self.cands]
        return max(ts) - min(ts)

    def __str__(self) -> str:
        b = self.best_cand
        return ("rank %d  N=%4d  DM %7.2f-%7.2f  best: DM=%7.2f "
                "sigma=%6.2f t=%10.4f" %
                (self.rank, self.numcands, self.min_dm, self.max_dm,
                 b.dm, b.sigma, b.time))


def auto_dm_thresh(cands: Sequence[SPCandidate]) -> float:
    """DM link distance from the trial spacing: the reference groups
    events on ADJACENT DM trials (rrattrap.py uses a trial-index
    neighborhood), so the equivalent absolute threshold is ~2 trial
    steps."""
    dms = np.unique([c.dm for c in cands])
    if dms.size < 2:
        return 0.5
    return 2.0 * float(np.median(np.diff(dms))) + 1e-9


def group_candidates(cands: Sequence[SPCandidate],
                     time_thresh: float = 0.1,
                     dm_thresh: Optional[float] = None
                     ) -> List[SinglePulseGroup]:
    """Greedy transitive grouping: events within time_thresh seconds
    AND dm_thresh DM units of any group member join that group
    (rrattrap.py Group creation semantics).  dm_thresh=None adapts to
    the DM trial spacing.  Implemented as a union-find sweep over
    time-sorted events for O(n·w) behavior instead of the reference's
    O(n^2) pairwise pass.
    """
    if dm_thresh is None:
        dm_thresh = auto_dm_thresh(cands)
    order = sorted(range(len(cands)), key=lambda i: cands[i].time)
    parent = list(range(len(cands)))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(i, j):
        ri, rj = find(i), find(j)
        if ri != rj:
            parent[ri] = rj

    # sliding window over time; pairwise check only inside the window
    for a in range(len(order)):
        ia = order[a]
        ta = cands[ia].time
        for b in range(a + 1, len(order)):
            ib = order[b]
            if cands[ib].time - ta > time_thresh:
                break
            if abs(cands[ib].dm - cands[ia].dm) <= dm_thresh:
                union(ia, ib)

    buckets: Dict[int, SinglePulseGroup] = {}
    for i in range(len(cands)):
        buckets.setdefault(find(i), SinglePulseGroup()).cands.append(
            cands[i])
    groups = list(buckets.values())
    for g in groups:
        g.cands.sort(key=lambda c: c.dm)
    return groups


def rank_groups(groups: Sequence[SinglePulseGroup],
                min_group: int = 30, min_dm: float = 2.0,
                sigma_thresh: float = 8.0) -> None:
    """Assign ranks in place (rrattrap.py rate-the-groups semantics)."""
    for g in groups:
        g.rank = _rank_one(g, min_group, min_dm, sigma_thresh)


def _rank_one(g: SinglePulseGroup, min_group: int, min_dm: float,
              sigma_thresh: float) -> int:
    if g.numcands < max(min_group // 6, 3):
        return 1
    if g.numcands < min_group:
        return 2
    dms = np.array([c.dm for c in g.cands])
    sig = np.array([c.sigma for c in g.cands])
    # sigma-vs-DM profile in 5 DM bands (the reference splits the span
    # and compares max sigma per band)
    edges = np.linspace(dms.min(), dms.max() + 1e-9, 6)
    band_max = np.zeros(5)
    for i in range(5):
        in_band = (dms >= edges[i]) & (dms < edges[i + 1])
        band_max[i] = sig[in_band].max() if in_band.any() else 0.0
    peak_band = int(np.argmax(band_max))
    peak = band_max[peak_band]
    edge = max(band_max[0], band_max[4])
    if peak_band in (0, 4):
        return 2                      # strongest at a DM edge: suspect
    if g.best_cand.dm < min_dm:
        return 2                      # peaks at ~zero DM: RFI-like
    rank = 3
    # rise-and-fall test with 5% slack (band maxima are noisy)
    rising = np.all(np.diff(band_max[:peak_band + 1]) >= -0.05 * peak)
    falling = np.all(np.diff(band_max[peak_band:]) <= 0.05 * peak)
    if rising and falling:
        rank = 4
    if rank == 4 and edge > 0 and peak / edge > 1.3:
        rank = 5
    if rank == 5 and peak >= 1.5 * sigma_thresh:
        rank = 6
    return rank


def read_and_group(paths: Sequence[str], time_thresh: float = 0.1,
                   dm_thresh: Optional[float] = None,
                   min_group: int = 30,
                   min_dm: float = 2.0, min_sigma: float = 0.0
                   ) -> List[SinglePulseGroup]:
    """rrattrap main flow: read many per-DM .singlepulse files, group,
    rank, and return groups sorted by (rank desc, max_sigma desc)."""
    from presto_tpu.search.singlepulse import read_singlepulse
    cands: List[SPCandidate] = []
    for p in paths:
        cands.extend(c for c in read_singlepulse(p)
                     if c.sigma >= min_sigma)
    groups = group_candidates(cands, time_thresh, dm_thresh)
    rank_groups(groups, min_group=min_group, min_dm=min_dm)
    groups.sort(key=lambda g: (-g.rank, -g.max_sigma))
    return groups


def write_groups(path: str, groups: Sequence[SinglePulseGroup],
                 min_rank: int = 0) -> None:
    """groups.txt artifact: one summary line + member rows per group."""
    with open(path, "w") as f:
        f.write("# rank N dm_lo dm_hi best_dm best_sigma best_time\n")
        for g in groups:
            if g.rank < min_rank:
                continue
            b = g.best_cand
            f.write("%d %d %.2f %.2f %.2f %.2f %.6f\n" % (
                g.rank, g.numcands, g.min_dm, g.max_dm, b.dm, b.sigma,
                b.time))
