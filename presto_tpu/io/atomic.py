"""Atomic, journaled artifact writes (io layer).

The survey driver's checkpoint contract is "a stage is skipped when
its outputs already exist", so a run killed mid-write must never leave
a half-written `.dat`/`.fft`/`.inf`/mask/ACCEL file that a resume
silently trusts.  Every artifact writer goes through atomic_open():
the bytes land in a same-directory temp file, are fsync'd, and only
then renamed over the target — on any crash (including an injected
SimulatedCrash, a BaseException) the target either keeps its previous
complete contents or does not exist at all.

file_checksum() is the companion: a streaming CRC-32 the survey
manifest records per completed artifact so a resume can verify instead
of trust (pipeline/manifest.py).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
import zlib
from typing import IO, Iterator

#: prefix of in-flight temp files; cleanup_stale_tmp() sweeps leftovers
TMP_PREFIX = ".pt-tmp."


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "wb") -> Iterator[IO]:
    """Open `path` for atomic replacement.

    Yields a real file object (usable with numpy .tofile); on normal
    exit the temp file is flushed, fsync'd, and renamed onto `path`.
    On ANY exception — Exception or BaseException alike, so injected
    crashes and KeyboardInterrupt count — the temp file is removed and
    `path` is untouched.
    """
    if mode not in ("wb", "w"):
        raise ValueError("atomic_open supports only 'w'/'wb', not %r"
                         % mode)
    target = os.path.abspath(path)
    d = os.path.dirname(target)
    fd, tmp = tempfile.mkstemp(
        prefix=TMP_PREFIX + os.path.basename(target) + ".", dir=d)
    f = os.fdopen(fd, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, target)
    except BaseException:
        try:
            f.close()
        except OSError:
            pass
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    else:
        _fsync_dir(d)


def _fsync_dir(d: str) -> None:
    """Flush the directory entry of a just-renamed artifact (ignored
    where the platform/filesystem does not support directory fds)."""
    try:
        dfd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    with atomic_open(path, "wb") as f:
        f.write(data)


def atomic_write_text(path: str, text: str) -> None:
    with atomic_open(path, "w") as f:
        f.write(text)


def file_checksum(path: str, chunk: int = 1 << 20) -> str:
    """Streaming CRC-32 of a file as 'crc32:xxxxxxxx'.

    CRC-32 (not a cryptographic hash) is the right tool here: the
    threat model is truncation and bit rot from a killed process or a
    flaky disk, not an adversary, and the manifest verify pass re-reads
    every artifact of a resumed survey — at survey artifact sizes the
    cheap checksum keeps resume latency negligible.
    """
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return "crc32:%08x" % (crc & 0xFFFFFFFF)


def cleanup_stale_tmp(dirpath: str) -> int:
    """Remove leftover atomic-write temp files (a killed process's
    in-flight writes).  Returns the number removed."""
    removed = 0
    try:
        names = os.listdir(dirpath)
    except OSError:
        return 0
    for name in names:
        if name.startswith(TMP_PREFIX):
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(dirpath, name))
                removed += 1
    return removed
