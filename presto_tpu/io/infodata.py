"""PRESTO `.inf` metadata sidecar files: read/write with format parity.

Every .dat / .fft artifact carries a `basename.inf` text sidecar.  The
format is the fixed-label key=value layout written by the reference's
writeinf (src/ioinf.c:257-350); fields mirror `struct infodata`
(include/makeinf.h:23-56).  Files written here are byte-compatible with
the reference for the radio-band case, so reference tools can consume
our artifacts and vice versa.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

ARTIFICIAL_TELESCOPE = "None (Artificial Data Set)"
_RADIO = "Radio"


@dataclass
class InfoData:
    """Python analog of struct infodata (makeinf.h:23-56)."""
    name: str = ""                       # data file name without suffix
    telescope: str = ARTIFICIAL_TELESCOPE
    instrument: str = "Unknown"
    object: str = "Unknown"
    ra_str: str = "00:00:00.0000"        # hh:mm:ss.ssss
    dec_str: str = "00:00:00.0000"       # dd:mm:ss.ssss
    observer: str = "Unknown"
    mjd_i: int = -1                      # epoch integer part
    mjd_f: float = 0.0                   # epoch fractional part
    bary: int = 0
    N: float = 0                         # number of bins
    dt: float = 0.0                      # seconds per bin
    numonoff: int = 1
    onoff: List[Tuple[float, float]] = field(default_factory=list)
    band: str = _RADIO
    fov: float = 0.0                     # beam diameter, arcsec
    dm: float = 0.0
    freq: float = 0.0                    # central freq of low channel, MHz
    freqband: float = 0.0                # total bandwidth, MHz
    num_chan: int = 1
    chan_wid: float = 0.0                # channel bandwidth, MHz
    analyzer: str = "Unknown"
    notes: str = ""

    @property
    def mjd(self) -> float:
        return self.mjd_i + self.mjd_f

    @property
    def is_artificial(self) -> bool:
        return self.telescope == ARTIFICIAL_TELESCOPE

    def basename(self) -> str:
        return self.name


def _fmt(label: str, value: str) -> str:
    # Label padded so '=' lands at index 40, matching writeinf
    # (ioinf.c:268-348) and the read fast path (ioinf.c:29).
    return " {:<39s}=  {}\n".format(label, value)


def write_inf(info: InfoData, filename: str | None = None) -> str:
    """Write `info` to `<name>.inf` (or `filename`).  Returns the path.

    Format parity: src/ioinf.c:257-350 writeinf.
    """
    path = filename or (info.name + ".inf")
    lines = []
    lines.append(_fmt("Data file name without suffix", info.name))
    lines.append(_fmt("Telescope used", info.telescope))
    if not info.is_artificial:
        lines.append(_fmt("Instrument used", info.instrument))
        lines.append(_fmt("Object being observed", info.object))
        lines.append(_fmt("J2000 Right Ascension (hh:mm:ss.ssss)",
                          info.ra_str))
        lines.append(_fmt("J2000 Declination     (dd:mm:ss.ssss)",
                          info.dec_str))
        lines.append(_fmt("Data observed by", info.observer))
        frac = "{:.15f}".format(info.mjd_f)
        assert frac.startswith("0.")
        lines.append(_fmt("Epoch of observation (MJD)",
                          "{:d}.{}".format(info.mjd_i, frac[2:])))
        lines.append(_fmt("Barycentered?           (1 yes, 0 no)",
                          str(info.bary)))
    lines.append(_fmt("Number of bins in the time series",
                      "{:<11.0f}".format(info.N)))
    lines.append(_fmt("Width of each time series bin (sec)",
                      "{:.15g}".format(info.dt)))
    breaks = 1 if info.numonoff > 1 else 0
    lines.append(_fmt("Any breaks in the data? (1 yes, 0 no)", str(breaks)))
    if info.numonoff > 1:
        for ii, (on, off) in enumerate(info.onoff):
            lines.append(_fmt("On/Off bin pair #{:3d}".format(ii + 1),
                              "{:<11.0f}, {:<11.0f}".format(on, off)))
    if not info.is_artificial:
        lines.append(_fmt("Type of observation (EM band)", info.band))
        if info.band == _RADIO:
            lines.append(_fmt("Beam diameter (arcsec)",
                              "{:.0f}".format(info.fov)))
            lines.append(_fmt("Dispersion measure (cm-3 pc)",
                              "{:.12g}".format(info.dm)))
            lines.append(_fmt("Central freq of low channel (MHz)",
                              "{:.12g}".format(info.freq)))
            lines.append(_fmt("Total bandwidth (MHz)",
                              "{:.12g}".format(info.freqband)))
            lines.append(_fmt("Number of channels",
                              "{:d}".format(info.num_chan)))
            lines.append(_fmt("Channel bandwidth (MHz)",
                              "{:.12g}".format(info.chan_wid)))
    lines.append(_fmt("Data analyzed by", info.analyzer))
    lines.append(" Any additional notes:\n    {}\n\n".format(info.notes))
    from presto_tpu.io.atomic import atomic_write_text
    atomic_write_text(path, "".join(lines))
    return path


def _val(line: str) -> str:
    """Extract the value after '=' the way read_inf_line_valstr does
    (ioinf.c:20-79): '=' at col 40 if present, else last '=' in line."""
    if len(line) > 40 and line[40] == "=":
        return line[41:].strip()
    idx = line.rfind("=")
    if idx < 0:
        raise ValueError("no '=' in .inf line: %r" % line)
    return line[idx + 1:].strip()


def read_inf(filenm: str) -> InfoData:
    """Read `<base>.inf` (accepts base name or full path with .inf)."""
    path = filenm if filenm.endswith(".inf") else filenm + ".inf"
    try:
        return _read_inf(path)
    except StopIteration:
        raise ValueError("truncated or malformed .inf file: %s" % path) \
            from None


def _read_inf(path: str) -> InfoData:
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()]
    it = iter([ln for ln in lines if ln.strip()])
    info = InfoData()
    info.name = _val(next(it))
    info.telescope = _val(next(it))
    if not info.is_artificial:
        info.instrument = _val(next(it))
        info.object = _val(next(it))
        info.ra_str = _val(next(it))
        info.dec_str = _val(next(it))
        info.observer = _val(next(it))
        mjd = _val(next(it))
        ipart, fpart = mjd.split(".")
        info.mjd_i = int(ipart)
        info.mjd_f = float("0." + fpart)
        info.bary = int(_val(next(it)))
    else:
        info.mjd_i = -1
        info.object = "fake pulsar"
    info.N = float(_val(next(it)))
    info.dt = float(_val(next(it)))
    breaks = int(_val(next(it)))
    info.onoff = []
    if breaks:
        while True:
            line = next(it)
            if "On/Off" not in line:
                pushed = line
                break
            on_s, off_s = _val(line).split(",")
            info.onoff.append((float(on_s), float(off_s)))
            if info.onoff[-1][1] >= info.N - 1:
                pushed = None
                break
        info.numonoff = len(info.onoff)
    else:
        info.numonoff = 1
        info.onoff = [(0.0, info.N - 1)]
        pushed = None
    rest = ([pushed] if pushed else []) + list(it)
    it = iter(rest)
    if not info.is_artificial:
        info.band = _val(next(it))
        if info.band == _RADIO:
            info.fov = float(_val(next(it)))
            info.dm = float(_val(next(it)))
            info.freq = float(_val(next(it)))
            info.freqband = float(_val(next(it)))
            info.num_chan = int(_val(next(it)))
            info.chan_wid = float(_val(next(it)))
    for line in it:
        if "Data analyzed by" in line:
            info.analyzer = _val(line)
        elif "Any additional notes" in line:
            break
    # notes: the indented line(s) after the marker
    try:
        marker = next(i for i, ln in enumerate(lines)
                      if "Any additional notes" in ln)
        info.notes = "\n".join(ln.strip() for ln in lines[marker + 1:]
                               if ln.strip())
    except StopIteration:
        pass
    return info


def ra_to_string(h: int, m: int, s: float) -> str:
    return "{:02d}:{:02d}:{:07.4f}".format(h, m, s)


def dec_to_string(d: int, m: int, s: float) -> str:
    sign = "-" if d < 0 else ""
    return "{}{:02d}:{:02d}:{:07.4f}".format(sign, abs(d), m, s)
