"""Ingest quarantine: data-quality accounting for the raw-data readers.

The readers (io/sigproc.py, io/psrfits.py) must not crash — or worse,
silently emit garbage — when an observation contains truncated reads,
NaN/Inf samples, or dropped/zero-filled blocks.  Instead each reader
carries a DataQualityReport: bad stretches are scrubbed to a pad value
on the way out, recorded here as typed intervals, and later converted
into rfifind mask entries (zap_intervals) so the whole downstream
pipeline treats detector damage exactly like RFI.

The report serializes to `<base>_quality.json` (written atomically) so
a survey's quarantine decisions are themselves a durable, inspectable
artifact.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.io.atomic import atomic_write_text

#: reasons a stretch of spectra can be quarantined.  "ring-drop" and
#: "stall" belong to the live-feed path (presto_tpu/stream/source.py):
#: blocks shed under ring-buffer backpressure, and zero-fill inserted
#: to hold real-time cadence across a producer stall.
REASONS = ("nan-inf", "zero-fill", "truncated", "dropped-rows",
           "short-read", "ring-drop", "stall")

#: minimum run of consecutive all-zero spectra flagged as zero-fill.
#: Real zero-fill (backend dropouts, padded gaps) comes in long runs;
#: a handful of legitimately-zero spectra in quantized noise must not
#: trigger quarantine.
ZERO_RUN_MIN = 64


@dataclass
class BadInterval:
    """[start, stop) spectra quarantined for `reason`."""
    start: int
    stop: int
    reason: str

    @property
    def nspectra(self) -> int:
        return self.stop - self.start

    def to_json(self) -> dict:
        return {"start": int(self.start), "stop": int(self.stop),
                "reason": self.reason}


@dataclass
class DataQualityReport:
    """Per-observation quarantine ledger (one per open reader)."""
    path: str = ""
    nspectra: int = 0
    nchan: int = 0
    intervals: List[BadInterval] = field(default_factory=list)
    #: samples (not spectra) individually scrubbed, e.g. isolated NaNs
    scrubbed_samples: int = 0

    # -- recording ----------------------------------------------------
    def add(self, start: int, stop: int, reason: str) -> None:
        """Record [start, stop) as bad; overlapping/adjacent intervals
        of the same reason merge so repeated reads of a region do not
        inflate the ledger."""
        if stop <= start:
            return
        start, stop = int(start), int(stop)
        merged = []
        for iv in self.intervals:
            if iv.reason == reason and iv.start <= stop \
                    and start <= iv.stop:
                start = min(start, iv.start)
                stop = max(stop, iv.stop)
            else:
                merged.append(iv)
        merged.append(BadInterval(start, stop, reason))
        merged.sort(key=lambda iv: (iv.start, iv.stop, iv.reason))
        self.intervals = merged

    # -- queries ------------------------------------------------------
    @property
    def clean(self) -> bool:
        return not self.intervals and not self.scrubbed_samples

    def bad_spectra(self) -> int:
        """Distinct spectra covered by any bad interval."""
        covered = 0
        last = -1
        for iv in sorted(self.intervals, key=lambda v: v.start):
            lo = max(iv.start, last)
            if iv.stop > lo:
                covered += iv.stop - lo
                last = iv.stop
        return covered

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for iv in self.intervals:
            out[iv.reason] = out.get(iv.reason, 0) + iv.nspectra
        return out

    def zap_intervals(self, ptsperint: int,
                      numint: Optional[int] = None) -> List[int]:
        """rfifind interval indices overlapping any bad stretch — the
        bridge from quarantine to the existing mask machinery."""
        if ptsperint <= 0:
            return []
        ints = set()
        for iv in self.intervals:
            lo = iv.start // ptsperint
            hi = (iv.stop - 1) // ptsperint
            ints.update(range(lo, hi + 1))
        if numint is not None:
            ints = {i for i in ints if 0 <= i < numint}
        return sorted(ints)

    def summary(self) -> str:
        if self.clean:
            return "data quality: clean"
        cnt = self.counts()
        frac = (self.bad_spectra() / self.nspectra
                if self.nspectra else 0.0)
        return ("data quality: %d/%d spectra quarantined (%.2f%%): %s"
                % (self.bad_spectra(), self.nspectra, 100 * frac,
                   ", ".join("%s=%d" % kv for kv in sorted(cnt.items()))))

    # -- metrics ------------------------------------------------------
    def publish(self, registry) -> None:
        """Fold this report's tallies into an obs MetricsRegistry so
        ingest health is visible on a live /metrics scrape, not only
        in per-run `<base>_quality.json` files:

          ingest_reports_total                one per published report
          ingest_scrubbed_samples_total       NaN/Inf samples scrubbed
          ingest_quarantined_spectra_total{reason=...}
                                              spectra per quarantine
                                              reason (zero-fill,
                                              short-read, ...)
        """
        registry.counter(
            "ingest_reports_total",
            "Data-quality reports published").inc()
        if self.scrubbed_samples:
            registry.counter(
                "ingest_scrubbed_samples_total",
                "Samples scrubbed (NaN/Inf replaced with pad)"
            ).inc(self.scrubbed_samples)
        counts = self.counts()
        if counts:
            c = registry.counter(
                "ingest_quarantined_spectra_total",
                "Spectra quarantined by the ingest readers",
                ("reason",))
            for reason, n in counts.items():
                c.labels(reason=reason).inc(n)

    # -- (de)serialization --------------------------------------------
    def to_json(self) -> dict:
        return {"path": self.path, "nspectra": int(self.nspectra),
                "nchan": int(self.nchan),
                "scrubbed_samples": int(self.scrubbed_samples),
                "bad_spectra": self.bad_spectra(),
                "counts": self.counts(),
                "intervals": [iv.to_json() for iv in self.intervals]}

    def write(self, path: str) -> str:
        atomic_write_text(path, json.dumps(self.to_json(), indent=1,
                                           sort_keys=True) + "\n")
        return path

    @classmethod
    def from_json(cls, obj: dict) -> "DataQualityReport":
        rep = cls(path=obj.get("path", ""),
                  nspectra=int(obj.get("nspectra", 0)),
                  nchan=int(obj.get("nchan", 0)),
                  scrubbed_samples=int(obj.get("scrubbed_samples", 0)))
        for iv in obj.get("intervals", []):
            rep.intervals.append(BadInterval(int(iv["start"]),
                                             int(iv["stop"]),
                                             str(iv["reason"])))
        return rep

    @classmethod
    def read(cls, path: str) -> "DataQualityReport":
        with open(path) as f:
            return cls.from_json(json.load(f))


def merge_reports(reports: Sequence[DataQualityReport],
                  path: str = "") -> DataQualityReport:
    out = DataQualityReport(path=path)
    for r in reports:
        out.nspectra = max(out.nspectra, r.nspectra)
        out.nchan = max(out.nchan, r.nchan)
        out.scrubbed_samples += r.scrubbed_samples
        for iv in r.intervals:
            out.add(iv.start, iv.stop, iv.reason)
    return out


# ----------------------------------------------------------------------
# Block scrubbers (shared by the readers' decode paths)
# ----------------------------------------------------------------------

def scrub_nonfinite(block: np.ndarray, start: int,
                    report: Optional[DataQualityReport],
                    padval: float = 0.0) -> np.ndarray:
    """Replace NaN/Inf samples with `padval`, recording the affected
    spectra (rows) as 'nan-inf' intervals.  Returns the block (scrubbed
    in place when writable, else a scrubbed copy)."""
    bad = ~np.isfinite(block)
    if not bad.any():
        return block
    if not block.flags.writeable:
        block = block.copy()
        bad = ~np.isfinite(block)
    nbad = int(bad.sum())
    block[bad] = padval
    if report is not None:
        report.scrubbed_samples += nbad
        rows = np.flatnonzero(bad.any(axis=1))
        for lo, hi in _runs(rows):
            report.add(start + lo, start + hi + 1, "nan-inf")
    return block


def record_zero_runs(block: np.ndarray, start: int,
                     report: Optional[DataQualityReport],
                     min_run: int = ZERO_RUN_MIN) -> None:
    """Record runs of >= min_run consecutive all-zero spectra as
    'zero-fill' (a backend dropout signature).  Detection only — the
    zeros stay, exactly like the reference's padded blocks; the mask
    integration is what removes them from the search."""
    if report is None or block.shape[0] < min_run:
        return
    zero_rows = np.flatnonzero(~block.any(axis=1))
    for lo, hi in _runs(zero_rows):
        if hi - lo + 1 >= min_run:
            report.add(start + lo, start + hi + 1, "zero-fill")


def _runs(indices: np.ndarray):
    """Yield (first, last) for each run of consecutive indices."""
    if indices.size == 0:
        return
    breaks = np.flatnonzero(np.diff(indices) > 1)
    starts = np.concatenate([[0], breaks + 1])
    ends = np.concatenate([breaks, [indices.size - 1]])
    for s, e in zip(starts, ends):
        yield int(indices[s]), int(indices[e])
