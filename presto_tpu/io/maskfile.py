"""rfifind mask / stats artifacts: binary parity with the reference.

Formats: mask file (mask.c:103-265 read_mask/write_mask), .stats file
(rfifind.c:600-617 write_statsfile).  Flag bits and the mask struct
mirror include/mask.h:1-29.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List

import numpy as np

# byte-mask flag bits (mask.h:1-10)
GOODDATA = 0x00
PADDING = 0x01
OLDMASK = 0x02
USERCHAN = 0x04
USERINTS = 0x08
BAD_POW = 0x10
BAD_STD = 0x20
BAD_AVG = 0x40
BADDATA = BAD_POW | BAD_STD | BAD_AVG
USERZAP = USERCHAN | USERINTS


@dataclass
class Mask:
    """Python analog of struct mask (mask.h:13-29)."""
    timesigma: float
    freqsigma: float
    mjd: float
    dtint: float
    lofreq: float
    dfreq: float
    numchan: int
    numint: int
    ptsperint: int
    zap_chans: np.ndarray = field(default_factory=lambda: np.array([], int))
    zap_ints: np.ndarray = field(default_factory=lambda: np.array([], int))
    chans_per_int: List[np.ndarray] = field(default_factory=list)

    def check_mask(self, starttime: float, duration: float):
        """Channels to mask for [starttime, starttime+duration) (s).

        Returns (-1, None) if everything is masked, else (n, channels).
        Parity: check_mask (mask.c:268-...).
        """
        loint = int(starttime / self.dtint)
        hiint = int((starttime + duration) / self.dtint)
        hiint = min(hiint, self.numint - 1)
        loint = min(loint, self.numint - 1)
        chans = set(self.zap_chans.tolist())
        for it in range(loint, hiint + 1):
            if it in self.zap_ints:
                return -1, None
            if it < len(self.chans_per_int):
                chans.update(self.chans_per_int[it].tolist())
        if len(chans) >= self.numchan:
            return -1, None
        return len(chans), np.array(sorted(chans), dtype=np.int32)

    def masked_fraction(self) -> float:
        total = self.numint * self.numchan
        zapped = len(self.zap_ints) * self.numchan
        for it in range(self.numint):
            if it in self.zap_ints:
                continue
            zapped += len(self.chans_per_int[it]) if \
                it < len(self.chans_per_int) else 0
        return zapped / max(total, 1)


def fill_mask(timesigma, freqsigma, mjd, dtint, lofreq, dfreq,
              numchan, numint, ptsperint, zap_chans, zap_ints,
              bytemask: np.ndarray) -> Mask:
    """Build a Mask from the bytemask: a channel is zapped in an
    interval when its BADDATA or USERZAP bits are set.
    Parity: fill_mask (mask.c:10-59)."""
    bad = (bytemask & (BADDATA | USERZAP)) != 0
    chans_per_int = [np.flatnonzero(bad[i]).astype(np.int32)
                     for i in range(numint)]
    return Mask(timesigma=timesigma, freqsigma=freqsigma, mjd=mjd,
                dtint=dtint, lofreq=lofreq, dfreq=dfreq, numchan=numchan,
                numint=numint, ptsperint=ptsperint,
                zap_chans=np.asarray(zap_chans, dtype=np.int32),
                zap_ints=np.asarray(zap_ints, dtype=np.int32),
                chans_per_int=chans_per_int)


def write_mask(path: str, m: Mask) -> None:
    """Binary parity: write_mask (mask.c:233-265); atomic on disk."""
    from presto_tpu.io.atomic import atomic_open
    with atomic_open(path, "wb") as f:
        f.write(struct.pack("<6d", m.timesigma, m.freqsigma, m.mjd,
                            m.dtint, m.lofreq, m.dfreq))
        f.write(struct.pack("<3i", m.numchan, m.numint, m.ptsperint))
        f.write(struct.pack("<i", len(m.zap_chans)))
        if len(m.zap_chans):
            np.asarray(m.zap_chans, "<i4").tofile(f)
        f.write(struct.pack("<i", len(m.zap_ints)))
        if len(m.zap_ints):
            np.asarray(m.zap_ints, "<i4").tofile(f)
        counts = np.array([len(c) for c in m.chans_per_int], "<i4")
        counts.tofile(f)
        for c in m.chans_per_int:
            # full-interval zaps are implicit (read reconstructs them)
            if 0 < len(c) < m.numchan:
                np.asarray(c, "<i4").tofile(f)


def read_mask(path: str) -> Mask:
    """Binary parity: read_mask (mask.c:103-148).  Truncated masks
    raise a typed PrestoIOError, not a bare struct.error."""
    from presto_tpu.io.errors import read_exact
    with open(path, "rb") as f:
        ts, fs, mjd, dtint, lofreq, dfreq = struct.unpack(
            "<6d", read_exact(f, 48, path, "mask header"))
        numchan, numint, ptsperint = struct.unpack(
            "<3i", read_exact(f, 12, path, "mask header"))
        nzc, = struct.unpack("<i", read_exact(f, 4, path,
                                              "mask header"))
        zap_chans = np.fromfile(f, "<i4", nzc) if nzc else \
            np.array([], np.int32)
        nzi, = struct.unpack("<i", read_exact(f, 4, path,
                                              "mask zap data"))
        zap_ints = np.fromfile(f, "<i4", nzi) if nzi else \
            np.array([], np.int32)
        counts = np.fromfile(f, "<i4", numint)
        chans = []
        for n in counts:
            if 0 < n < numchan:
                chans.append(np.fromfile(f, "<i4", n))
            elif n == numchan:
                chans.append(np.arange(numchan, dtype=np.int32))
            else:
                chans.append(np.array([], np.int32))
    return Mask(timesigma=ts, freqsigma=fs, mjd=mjd, dtint=dtint,
                lofreq=lofreq, dfreq=dfreq, numchan=numchan,
                numint=numint, ptsperint=ptsperint, zap_chans=zap_chans,
                zap_ints=zap_ints, chans_per_int=chans)


def write_statsfile(path: str, datapow, dataavg, datastd, ptsperint,
                    lobin=0, numbetween=2) -> None:
    """Binary parity: write_statsfile (rfifind.c:600-617).
    datapow/avg/std: [numint, numchan] float32; atomic on disk."""
    from presto_tpu.io.atomic import atomic_open
    numint, numchan = datapow.shape
    with atomic_open(path, "wb") as f:
        f.write(struct.pack("<5i", numchan, numint, ptsperint, lobin,
                            numbetween))
        np.asarray(datapow, "<f4").tofile(f)
        np.asarray(dataavg, "<f4").tofile(f)
        np.asarray(datastd, "<f4").tofile(f)


def read_statsfile(path: str):
    with open(path, "rb") as f:
        numchan, numint, ptsperint, lobin, numbetween = struct.unpack(
            "<5i", f.read(20))
        n = numchan * numint
        datapow = np.fromfile(f, "<f4", n).reshape(numint, numchan)
        dataavg = np.fromfile(f, "<f4", n).reshape(numint, numchan)
        datastd = np.fromfile(f, "<f4", n).reshape(numint, numchan)
    return dict(numchan=numchan, numint=numint, ptsperint=ptsperint,
                lobin=lobin, numbetween=numbetween, datapow=datapow,
                dataavg=dataavg, datastd=datastd)


def determine_padvals(statsfile_path: str) -> np.ndarray:
    """Per-channel padding values = middle-80% clipped mean of each
    channel's per-interval averages (determine_padvals, mask.c:177-...)."""
    st = read_statsfile(statsfile_path)
    avg = np.sort(st["dataavg"], axis=0)      # [numint, numchan]
    numint = st["numint"]
    lo = int(0.1 * numint)
    hi = max(lo + 1, int(0.9 * numint))
    return avg[lo:hi].mean(axis=0).astype(np.float32)
