""".mak parameter files (iomak.c / makeinf.c analog).

The reference's synthetic ground-truth system: a .mak file declares an
exact signal (N, dt, shape, f/fdot/fdotdot, amplitude, phase, binary
orbit, amplitude modulation, noise, on/off windows) and makedata
renders it to .dat+.inf (tests/test_fdot.mak etc., SURVEY §4 item 2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple


@dataclass
class MakParams:
    description: str = "makedata parameters"
    N: int = 0
    dt: float = 1.0
    shape: str = "Sine"            # Sine | Gaussian | Crab | ...
    roundformat: str = "Whole Numbers"   # or "Fractional"
    f: float = 1.0
    fdot: float = 0.0
    fdotdot: float = 0.0
    amp: float = 1.0
    phs_deg: float = 0.0
    dc: float = 0.0
    orb_p: float = 0.0
    orb_x: float = 0.0
    orb_e: float = 0.0
    orb_w: float = 0.0
    orb_t: float = 0.0
    ampmod_a: float = 0.0
    ampmod_phs_deg: float = 0.0
    ampmod_f: float = 0.0
    noise_type: str = "Standard"   # Standard (gaussian) | Other
    noise_sigma: float = 1.0
    onoff: List[Tuple[float, float]] = field(default_factory=list)
    fwhm: float = 0.1              # gaussian pulse FWHM (rotations)


_KEYMAP = [
    ("Num data pts", "N", int),
    ("dt per bin (s)", "dt", float),
    ("Pulse shape", "shape", str),
    ("Rounding format", "roundformat", str),
    ("Pulse freq (hz)", "f", float),
    ("fdot (s-2)", "fdot", float),
    ("fdotdot (s-3)", "fdotdot", float),
    ("Pulse amp", "amp", float),
    ("Pulse phs (deg)", "phs_deg", float),
    ("DC backgrnd level", "dc", float),
    ("Binary period (s)", "orb_p", float),
    ("Bin asini/c (s)", "orb_x", float),
    ("Bin eccentricity", "orb_e", float),
    ("Ang of Peri (deg)", "orb_w", float),
    ("Tm since peri (s)", "orb_t", float),
    ("Amp Mod amplitude", "ampmod_a", float),
    ("Amp Mod phs (deg)", "ampmod_phs_deg", float),
    ("Amp Mod freq (hz)", "ampmod_f", float),
    ("Noise type", "noise_type", str),
    ("Noise sigma", "noise_sigma", float),
    ("Gauss FWHM", "fwhm", float),
]


def read_mak(path: str) -> MakParams:
    mk = MakParams()
    keymap = {k: (attr, typ) for k, attr, typ in _KEYMAP}
    with open(path) as f:
        lines = f.read().splitlines()
    if lines and "=" not in lines[0]:
        mk.description = lines[0].strip()
        lines = lines[1:]
    for line in lines:
        if "=" not in line:
            continue
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if key.startswith("On/Off Pair"):
            a, b = val.split()
            mk.onoff.append((float(a), float(b)))
            continue
        if key in keymap:
            attr, typ = keymap[key]
            setattr(mk, attr, typ(val))
    if not mk.onoff:
        mk.onoff = [(0.0, 1.0)]
    return mk


def write_mak(path: str, mk: MakParams) -> None:
    with open(path, "w") as f:
        f.write(mk.description + "\n")
        for key, attr, typ in _KEYMAP:
            val = getattr(mk, attr)
            f.write("%-17s = %s\n" % (key, ("%.17g" % val)
                                      if typ is not str else val))
        for i, (a, b) in enumerate(mk.onoff, 1):
            f.write("On/Off Pair %2d    = %g %g\n" % (i, a, b))
