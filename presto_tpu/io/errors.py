"""Typed I/O errors for the ingest layer.

The reference's readers fail truncated/corrupt inputs with bare
struct.error / EOFError escapes deep inside the format parsers; CLI
tools then die with a traceback that names a line of C-port code
instead of the broken file.  PrestoIOError carries the file, offset,
and expected/actual byte counts so every layer above (apps, pipeline,
serve) can print a one-line diagnosis or convert the failure into a
quarantine decision.
"""

from __future__ import annotations

from typing import Optional


class PrestoIOError(IOError):
    """Unrecoverable raw-data / artifact corruption.

    Attributes
    ----------
    path : file the failure occurred in (may be "" when unknown)
    offset : byte offset of the failed read, or None
    expected_bytes / actual_bytes : size of the short read, or None
    kind : short machine-readable tag ("truncated-header",
        "truncated-data", "bad-magic", "size-mismatch", ...)
    """

    def __init__(self, message: str, path: str = "",
                 offset: Optional[int] = None,
                 expected_bytes: Optional[int] = None,
                 actual_bytes: Optional[int] = None,
                 kind: str = "io"):
        self.message = message
        self.path = path
        self.offset = offset
        self.expected_bytes = expected_bytes
        self.actual_bytes = actual_bytes
        self.kind = kind
        super().__init__(str(self))

    def __str__(self) -> str:
        parts = []
        if self.path:
            parts.append("%s:" % self.path)
        parts.append(self.message)
        detail = []
        if self.offset is not None:
            detail.append("at byte %d" % self.offset)
        if self.expected_bytes is not None:
            got = (self.actual_bytes
                   if self.actual_bytes is not None else 0)
            detail.append("expected %d bytes, got %d"
                          % (self.expected_bytes, got))
        if detail:
            parts.append("(%s)" % ", ".join(detail))
        return " ".join(parts)


def read_exact(f, nbytes: int, path: str = "",
               what: str = "data") -> bytes:
    """Read exactly `nbytes` or raise a typed PrestoIOError naming the
    short read — the hardening wrapper every binary parser uses in
    place of a bare f.read()/struct.unpack pair."""
    offset = None
    try:
        offset = f.tell()
    except (OSError, AttributeError):
        pass
    data = f.read(nbytes)
    if len(data) != nbytes:
        raise PrestoIOError("truncated %s" % what, path=path,
                            offset=offset, expected_bytes=nbytes,
                            actual_bytes=len(data),
                            kind="truncated-" + ("header"
                                                 if "header" in what
                                                 else "data"))
    return data
