""".bestprof reader (lib/python/bestprof.py analog).

Parses the text files written by io/pfd.write_bestprof / the reference
prepfold: '#'-prefixed key = value header lines followed by
'bin  value' profile rows.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class Bestprof:
    filenm: str = ""
    candnm: str = ""
    telescope: str = ""
    epochi: int = 0            # integer part of topo epoch
    epochf: float = 0.0        # fractional part
    bepoch: float = 0.0
    dt: float = 0.0
    N: float = 0.0
    data_avg: float = 0.0
    data_std: float = 0.0
    proflen: int = 0
    prof_avg: float = 0.0
    prof_std: float = 0.0
    chi_sqr: float = 0.0
    best_dm: float = 0.0
    p0_topo: float = 0.0       # seconds
    p0err_topo: float = 0.0
    p1_topo: float = 0.0       # s/s
    p1err_topo: float = 0.0
    profile: np.ndarray = field(default_factory=lambda: np.zeros(0))

    @property
    def epoch(self) -> float:
        return self.epochi + self.epochf


def _pm_split(val: str):
    if "+/-" in val:
        a, b = val.split("+/-")
        return float(a), float(b)
    return float(val), 0.0


def read_bestprof(path: str) -> Bestprof:
    bp = Bestprof()
    prof = []
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("#") and "=" in line:
                key, _, val = line[1:].partition("=")
                key = key.strip()
                val = val.strip()
                if val in ("", "N/A"):
                    continue
                if key == "Input file":
                    bp.filenm = val
                elif key == "Candidate":
                    bp.candnm = val
                elif key == "Telescope":
                    bp.telescope = val
                elif key == "Epoch_topo":
                    e = float(val)
                    bp.epochi = int(e)
                    bp.epochf = e - bp.epochi
                elif key.startswith("Epoch_bary"):
                    bp.bepoch = float(val)
                elif key == "T_sample":
                    bp.dt = float(val)
                elif key == "Data Folded":
                    bp.N = float(val)
                elif key == "Data Avg":
                    bp.data_avg = float(val)
                elif key == "Data StdDev":
                    bp.data_std = float(val)
                elif key == "Profile Bins":
                    bp.proflen = int(val)
                elif key == "Profile Avg":
                    bp.prof_avg = float(val)
                elif key == "Profile StdDev":
                    bp.prof_std = float(val)
                elif key == "Reduced chi-sqr":
                    bp.chi_sqr = float(val)
                elif key == "Best DM":
                    bp.best_dm = float(val)
                elif key.startswith("P_topo"):
                    v, e = _pm_split(val)
                    bp.p0_topo, bp.p0err_topo = v / 1000.0, e / 1000.0
                elif key.startswith("P'_topo"):
                    bp.p1_topo, bp.p1err_topo = _pm_split(val)
            elif line and not line.startswith("#"):
                parts = line.split()
                if len(parts) == 2:
                    prof.append(float(parts[1]))
    bp.profile = np.array(prof)
    if not bp.proflen:
        bp.proflen = len(prof)
    return bp
