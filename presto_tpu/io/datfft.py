"""`.dat` (float32 time series) and `.fft` (packed complex64) file I/O.

Artifact parity with the reference: a `.dat` is raw little-endian
float32 samples; a `.fft` is the NR-packed real FFT written by realfft
(src/fastffts.c:198-270): n/2 complex64 values where element 0 holds
(DC, Nyquist) packed as (re, im) and elements 1..n/2-1 are the positive
-frequency amplitudes.  Both carry a `.inf` sidecar.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.io.infodata import InfoData, read_inf, write_inf


def write_dat(path: str, data: np.ndarray, info: InfoData | None = None):
    data.astype(np.float32).tofile(path)
    if info is not None:
        base = path[:-4] if path.endswith(".dat") else path
        info.name = base
        info.N = data.size
        write_inf(info, base + ".inf")


def read_dat(path: str) -> np.ndarray:
    return np.fromfile(path, dtype=np.float32)


def read_dat_with_inf(path: str):
    base = path[:-4] if path.endswith(".dat") else path
    return np.fromfile(base + ".dat", dtype=np.float32), read_inf(base)


def write_fft(path: str, packed: np.ndarray, info: InfoData | None = None):
    """packed: complex64 array of n/2 NR-packed amplitudes."""
    packed.astype(np.complex64).tofile(path)
    if info is not None:
        base = path[:-4] if path.endswith(".fft") else path
        info.name = base
        write_inf(info, base + ".inf")


def read_fft(path: str) -> np.ndarray:
    return np.fromfile(path, dtype=np.complex64)
