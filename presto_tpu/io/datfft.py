"""`.dat` (float32 time series) and `.fft` (packed complex64) file I/O.

Artifact parity with the reference: a `.dat` is raw little-endian
float32 samples; a `.fft` is the NR-packed real FFT written by realfft
(src/fastffts.c:198-270): n/2 complex64 values where element 0 holds
(DC, Nyquist) packed as (re, im) and elements 1..n/2-1 are the positive
-frequency amplitudes.  Both carry a `.inf` sidecar.

All writes are atomic (tmp + fsync + rename, io/atomic.py) so a killed
prepsubband/realfft never leaves a truncated artifact under its final
name; reads validate element alignment and (when a sidecar is
available) the sample count, raising a typed PrestoIOError on
mismatch instead of silently returning a short series.
"""

from __future__ import annotations

import os

import numpy as np

from presto_tpu.io.atomic import atomic_open
from presto_tpu.io.errors import PrestoIOError
from presto_tpu.io.infodata import InfoData, read_inf, write_inf


def _check_aligned(path: str, itemsize: int, what: str) -> int:
    """File size must be a whole number of `itemsize`-byte elements;
    returns the element count."""
    size = os.path.getsize(path)
    if size % itemsize:
        raise PrestoIOError(
            "truncated %s (size %d is not a multiple of %d)"
            % (what, size, itemsize), path=path,
            expected_bytes=(size // itemsize + 1) * itemsize,
            actual_bytes=size, kind="truncated-data")
    return size // itemsize


def write_dat(path: str, data: np.ndarray, info: InfoData | None = None):
    with atomic_open(path, "wb") as f:
        data.astype(np.float32).tofile(f)
    if info is not None:
        base = path[:-4] if path.endswith(".dat") else path
        info.name = base
        info.N = data.size
        write_inf(info, base + ".inf")


def write_sdat(path: str, data: np.ndarray,
               info: InfoData | None = None):
    """Raw int16 `.sdat` with prepdata -shorts semantics
    (prepdata.c:696-744): subtract offset = floor(mean); if the dynamic
    range slightly exceeds int16 (< 1.5x) clip the low values by using
    offset = max - SHRT_MAX; if it is way too large, refuse (return
    None so the caller keeps floats).  Returns the applied offset.
    C-cast truncation toward zero is preserved via np.trunc."""
    avg, mx, mn = float(data.mean()), float(data.max()), float(data.min())
    offset = float(np.floor(avg))
    if (mx - mn) > 65535.0:
        if (mx - mn) < 1.5 * 65535.0:
            offset = mx - 32767.0
        else:
            return None
    q = np.trunc(data.astype(np.float64) + 1e-20 - offset)
    q = np.clip(q, -32768, 32767).astype("<i2")
    with atomic_open(path, "wb") as f:
        q.tofile(f)
    if info is not None:
        base = path[:-5] if path.endswith(".sdat") else path
        info.name = base
        info.N = data.size
        write_inf(info, base + ".inf")
    return offset


def read_dat(path: str, expected_n: int | None = None) -> np.ndarray:
    n = _check_aligned(path, 4, ".dat time series")
    if expected_n is not None and n != expected_n:
        raise PrestoIOError(
            ".dat sample count %d != expected %d" % (n, expected_n),
            path=path, expected_bytes=4 * expected_n,
            actual_bytes=4 * n, kind="size-mismatch")
    return np.fromfile(path, dtype=np.float32)


def read_dat_with_inf(path: str):
    """(.dat samples, InfoData), cross-checked: a sample count that
    disagrees with the sidecar's N means the pair is torn (one of the
    two updated, the other not) and raises PrestoIOError."""
    base = path[:-4] if path.endswith(".dat") else path
    info = read_inf(base)
    data = read_dat(base + ".dat", expected_n=int(info.N))
    return data, info


def write_fft(path: str, packed: np.ndarray, info: InfoData | None = None):
    """packed: complex64 array of n/2 NR-packed amplitudes."""
    with atomic_open(path, "wb") as f:
        packed.astype(np.complex64).tofile(f)
    if info is not None:
        base = path[:-4] if path.endswith(".fft") else path
        info.name = base
        write_inf(info, base + ".inf")


def read_fft(path: str, expected_n: int | None = None) -> np.ndarray:
    n = _check_aligned(path, 8, ".fft spectrum")
    if expected_n is not None and n != expected_n:
        raise PrestoIOError(
            ".fft amplitude count %d != expected %d" % (n, expected_n),
            path=path, expected_bytes=8 * expected_n,
            actual_bytes=8 * n, kind="size-mismatch")
    return np.fromfile(path, dtype=np.complex64)
