"""`.dat` (float32 time series) and `.fft` (packed complex64) file I/O.

Artifact parity with the reference: a `.dat` is raw little-endian
float32 samples; a `.fft` is the NR-packed real FFT written by realfft
(src/fastffts.c:198-270): n/2 complex64 values where element 0 holds
(DC, Nyquist) packed as (re, im) and elements 1..n/2-1 are the positive
-frequency amplitudes.  Both carry a `.inf` sidecar.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.io.infodata import InfoData, read_inf, write_inf


def write_dat(path: str, data: np.ndarray, info: InfoData | None = None):
    data.astype(np.float32).tofile(path)
    if info is not None:
        base = path[:-4] if path.endswith(".dat") else path
        info.name = base
        info.N = data.size
        write_inf(info, base + ".inf")


def write_sdat(path: str, data: np.ndarray,
               info: InfoData | None = None):
    """Raw int16 `.sdat` with prepdata -shorts semantics
    (prepdata.c:696-744): subtract offset = floor(mean); if the dynamic
    range slightly exceeds int16 (< 1.5x) clip the low values by using
    offset = max - SHRT_MAX; if it is way too large, refuse (return
    None so the caller keeps floats).  Returns the applied offset.
    C-cast truncation toward zero is preserved via np.trunc."""
    avg, mx, mn = float(data.mean()), float(data.max()), float(data.min())
    offset = float(np.floor(avg))
    if (mx - mn) > 65535.0:
        if (mx - mn) < 1.5 * 65535.0:
            offset = mx - 32767.0
        else:
            return None
    q = np.trunc(data.astype(np.float64) + 1e-20 - offset)
    q = np.clip(q, -32768, 32767).astype("<i2")
    q.tofile(path)
    if info is not None:
        base = path[:-5] if path.endswith(".sdat") else path
        info.name = base
        info.N = data.size
        write_inf(info, base + ".inf")
    return offset


def read_dat(path: str) -> np.ndarray:
    return np.fromfile(path, dtype=np.float32)


def read_dat_with_inf(path: str):
    base = path[:-4] if path.endswith(".dat") else path
    return np.fromfile(base + ".dat", dtype=np.float32), read_inf(base)


def write_fft(path: str, packed: np.ndarray, info: InfoData | None = None):
    """packed: complex64 array of n/2 NR-packed amplitudes."""
    packed.astype(np.complex64).tofile(path)
    if info is not None:
        base = path[:-4] if path.endswith(".fft") else path
        info.name = base
        write_inf(info, base + ".inf")


def read_fft(path: str) -> np.ndarray:
    return np.fromfile(path, dtype=np.complex64)
