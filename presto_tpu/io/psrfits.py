"""PSRFITS search-mode reader (+ synthesizer for tests/converters).

Reference: src/psrfits.c.  Semantics reproduced:
  - primary-HDU observation metadata + SUBINT-HDU geometry
    (read_PSRFITS_files, psrfits.c:103-660): TBIN/NCHAN/NPOL/NSBLK/
    NBITS/NAXIS2/NSUBOFFS, ZERO_OFF, CHAN_DM, DAT_FREQ-derived band
    orientation (flip ascending bands to PRESTO's descending layout),
    start-time stitching of multiple files via STT_*MJD + OFFS_SUB
  - dropped/missing subint detection via OFFS_SUB discrepancy with
    per-channel padding (get_PSRFITS_rawblock, psrfits.c:663-786)
  - 1/2/4/8/16/32-bit sample unpack (psrfits.c:828-866) — vectorized
    numpy here instead of the OpenMP loops; the C++ feeder
    (presto_tpu.native) is the high-throughput path
  - DAT_SCL/DAT_OFFS/DAT_WTS application with ZERO_OFF
    (psrfits.c:899-908) and polarization summing (AABB/2-pol) or
    selection (psrfits.c:887-...)

The class exposes the FilterbankFile protocol (header/read_spectra/
nspectra) with frequency-ascending [n, nchan] float32 blocks, so every
app's reader dispatch works on PSRFITS unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from presto_tpu.io import native
from presto_tpu.io.errors import PrestoIOError
from presto_tpu.io.fitsio import FitsFile, write_fits
from presto_tpu.io.quality import (DataQualityReport, record_zero_runs,
                                   scrub_nonfinite)
from presto_tpu.io.sigproc import FilterbankHeader

SECPERDAY = 86400.0


def _ra_str_to_sigproc(s) -> float:
    """RA string ('hh:mm:ss.s', 'hh mm ss.s', or numeric hours) ->
    SIGPROC packed hhmmss.s — via the shared coordinate parser
    (astro/bary.parse_ra) instead of a third hand-rolled split."""
    from presto_tpu.astro.bary import parse_ra
    from presto_tpu.utils.psr import rad_to_hms
    try:
        if isinstance(s, str) and ":" not in s and " " not in s.strip():
            # Bare number in a string: hours by convention — but some
            # PSRFITS writers store decimal DEGREES here.  Values
            # >= 24 cannot be hours: treat as degrees (ADVICE r4);
            # the ambiguous 0-24 range stays hours (documented
            # convention), values in it are wrong by 15x only for
            # degree-writing sources within 24 deg of RA 0.
            v = float(s)
            rad = v * np.pi / (12.0 if abs(v) < 24.0 else 180.0)
        else:
            rad = parse_ra(s)
    except (ValueError, IndexError, TypeError):
        return 0.0
    h, m, sec = rad_to_hms(rad)
    return h * 10000.0 + m * 100.0 + sec


def _dec_str_to_sigproc(s) -> float:
    """DEC string ('[+-]dd:mm:ss.s', spaces, or numeric degrees) ->
    SIGPROC packed [+-]ddmmss.s."""
    from presto_tpu.astro.bary import parse_dec
    from presto_tpu.utils.psr import rad_to_dms
    try:
        if isinstance(s, str) and ":" not in s and " " not in s.strip():
            rad = float(s) * np.pi / 180.0
        else:
            rad = parse_dec(s)
    except (ValueError, IndexError, TypeError):
        return 0.0
    d, m, sec = rad_to_dms(rad)
    sign = -1.0 if d < 0 or (d == 0 and rad < 0) else 1.0
    return sign * (abs(d) * 10000.0 + m * 100.0 + sec)


def unpack_samples(raw: np.ndarray, nbits: int) -> np.ndarray:
    """Packed big-endian-bit samples -> uint8/uint16/etc array.
    Vectorized analog of the unpack loops (psrfits.c:828-866)."""
    raw = np.asarray(raw, np.uint8)
    if nbits == 8:
        return raw
    if nbits == 4:
        out = np.empty(raw.size * 2, np.uint8)
        out[0::2] = raw >> 4
        out[1::2] = raw & 0x0F
        return out
    if nbits == 2:
        out = np.empty(raw.size * 4, np.uint8)
        for i, sh in enumerate((6, 4, 2, 0)):
            out[i::4] = (raw >> sh) & 0x03
        return out
    if nbits == 1:
        return np.unpackbits(raw)
    if nbits == 16:
        return raw.view(">i2").astype(np.int32)
    if nbits == 32:
        return raw.view(">f4").astype(np.float32)
    raise ValueError("unsupported NBITS=%d" % nbits)


@dataclass
class PsrfitsMeta:
    """Per-file SUBINT geometry (spectra_info analog for one file)."""
    path: str
    nsubint: int
    start_subint: int        # rows missing before this file's first row
    start_spec: int          # spectrum index of first row rel. to obs
    start_mjd: float


class PsrfitsFile:
    """One or more PSRFITS files as a contiguous observation."""

    def __init__(self, paths, apply_weight: Optional[bool] = None,
                 apply_scale: Optional[bool] = None,
                 apply_offset: Optional[bool] = None,
                 use_poln: int = 0, quarantine: bool = True):
        if isinstance(paths, str):
            paths = [paths]
        self.paths = list(paths)
        self.files: List[FitsFile] = []
        self.meta: List[PsrfitsMeta] = []
        self.use_poln = use_poln
        self.quarantine = quarantine
        try:
            self._open_all()
        except (KeyError, TypeError) as e:
            # a missing HDU/column (SUBINT, TBIN, DATA...) or a card
            # whose value rotted to the wrong type is file corruption,
            # not a dict bug: surface it typed
            self.close()
            raise PrestoIOError(
                "missing/corrupt PSRFITS structure: %s" % e,
                path=self.paths[0], kind="bad-header") from None
        self._auto_scaling(apply_weight, apply_scale, apply_offset)
        self._cache_row = (None, None)
        self._init_quality()

    # -- setup --------------------------------------------------------
    def _open_all(self):
        first = True
        for path in self.paths:
            ff = FitsFile(path)
            pri = ff.primary
            sub = ff.hdu("SUBINT")
            h = sub.header
            if first:
                obs_mode = str(pri.get("OBS_MODE", "SEARCH")).strip()
                if obs_mode == "SRCH":        # Parkes DFB quirk
                    obs_mode = "SEARCH"
                if obs_mode != "SEARCH":
                    raise ValueError("%s is not SEARCH-mode PSRFITS"
                                     % path)
                self.dt = float(h["TBIN"])
                self.nchan = int(h["NCHAN"])
                self.npol = int(h.get("NPOL", 1))
                self.poln_order = str(h.get("POL_TYPE", "AA+BB")).strip()
                self.nsblk = int(h["NSBLK"])
                self.nbits = int(h.get("NBITS", 8))
                if (self.nchan <= 0 or self.nsblk <= 0
                        or self.dt <= 0.0
                        or self.nbits not in (1, 2, 4, 8, 16, 32)):
                    raise PrestoIOError(
                        "invalid SUBINT geometry (NCHAN=%d NSBLK=%d "
                        "TBIN=%g NBITS=%d)" % (self.nchan, self.nsblk,
                                               self.dt, self.nbits),
                        path=path, kind="bad-header")
                self.zero_offset = abs(float(h.get("ZERO_OFF", 0.0) or 0.0))
                self.chan_dm = float(pri.get("CHAN_DM", 0.0) or 0.0)
                self.source = str(pri.get("SRC_NAME", "")).strip()
                self.telescope = str(pri.get("TELESCOP", "")).strip()
                self.ra_str = str(pri.get("RA", "")).strip()
                self.dec_str = str(pri.get("DEC", "")).strip()
                freqs = np.asarray(sub.read_col("DAT_FREQ", 0),
                                   np.float64)
                if len(freqs) >= 2:
                    self.df = float(freqs[1] - freqs[0])
                else:
                    self.df = float(pri.get("OBSBW", 1.0)) / self.nchan
                self.freqs = freqs
                self.fctr = float(pri.get("OBSFREQ",
                                          freqs.mean() if len(freqs)
                                          else 0.0))
            imjd = int(pri.get("STT_IMJD", 55000))
            smjd = int(pri.get("STT_SMJD", 0))
            offs = float(pri.get("STT_OFFS", 0.0) or 0.0)
            start_mjd = imjd + (smjd + offs) / SECPERDAY
            nsub = sub.naxis2
            nsuboffs = int(h.get("NSUBOFFS", 0) or 0)
            tsub = self.dt * self.nsblk
            # OFFS_SUB of row 1 overrides NSUBOFFS (psrfits.c:253-287)
            offs_sub0 = float(sub.read_col("OFFS_SUB", 0)[0])
            if offs_sub0 != 0.0:
                # ROUND like the row-grid snap in _row_start_spec so
                # negative OFFS_SUB drift on a leading dropped row
                # cannot place the file origin one subint early
                numrows = int(round((offs_sub0 - 0.5 * tsub) / tsub))
                start_subint = numrows
                self._offs_sub_zero = False
            else:
                start_subint = nsuboffs
                self._offs_sub_zero = True
            start_mjd += (tsub * start_subint) / SECPERDAY
            if first:
                start_spec = 0
                self.start_mjd = start_mjd
            else:
                dmjd = start_mjd - self.meta[0].start_mjd
                if dmjd < 0:
                    raise ValueError("PSRFITS files out of time order")
                start_spec = int(round(dmjd * SECPERDAY / self.dt))
            self.files.append(ff)
            self.meta.append(PsrfitsMeta(
                path=path, nsubint=nsub, start_subint=start_subint,
                start_spec=start_spec, start_mjd=start_mjd))
            first = False
        # Cache every row's absolute start spectrum once (one pass per
        # file) so read_spectra can binary-search instead of re-reading
        # OFFS_SUB per row per call (O(nsubint * nblocks) otherwise).
        self._row_specs = []
        for fi, m in enumerate(self.meta):
            self._row_specs.append(np.asarray(
                [self._row_start_spec_uncached(fi, r)
                 for r in range(m.nsubint)], dtype=np.int64))
        last = self.meta[-1]
        self.N = last.start_spec + self._last_spec_of(len(self.meta) - 1)
        self.padvals = np.zeros(self.nchan, np.float32)

    def _init_quality(self) -> None:
        """Build the quarantine ledger; pad gaps the row geometry
        already implies (dropped subints, inter-file holes) are
        recorded up front so the report is complete even before any
        data is read."""
        self.quality = DataQualityReport(path=self.paths[0],
                                         nspectra=int(self.N),
                                         nchan=self.nchan)
        covered = sorted((int(s), int(s) + self.nsblk)
                         for specs in self._row_specs for s in specs)
        pos = 0
        for lo, hi in covered:
            if lo > pos:
                self.quality.add(pos, lo, "dropped-rows")
            pos = max(pos, hi)

    def _last_spec_of(self, fi: int) -> int:
        """Spectrum index just past file fi's last row (rel. to file
        start), honoring OFFS_SUB row positions."""
        ff, m = self.files[fi], self.meta[fi]
        sub = ff.hdu("SUBINT")
        row_spec = self._row_start_spec(fi, m.nsubint - 1) - m.start_spec
        return row_spec + self.nsblk

    def _auto_scaling(self, w, s, o):
        """Default scale/offset/weight policy: apply when non-trivial
        (the reference asks the user; auto-detection is kinder)."""
        sub = self.files[0].hdu("SUBINT")
        try:
            scales = sub.read_col("DAT_SCL", 0)
            offsets = sub.read_col("DAT_OFFS", 0)
            weights = sub.read_col("DAT_WTS", 0)
            self.apply_scale = bool(np.any(scales != 1.0)) if s is None \
                else s
            self.apply_offset = bool(np.any(offsets != 0.0)) if o is None \
                else o
            self.apply_weight = bool(np.any(weights != 1.0)) if w is None \
                else w
        except KeyError:
            self.apply_scale = self.apply_offset = self.apply_weight = \
                False

    # -- FilterbankFile protocol --------------------------------------
    @property
    def header(self) -> FilterbankHeader:
        # read_spectra always presents ascending frequency, so the
        # header describes the band with fch1 = lowest center, foff > 0
        # (same convention FilterbankFile ends up with post-flip).
        return FilterbankHeader(
            source_name=self.source or "Unknown",
            nchans=self.nchan, nbits=self.nbits,
            fch1=float(self.freqs.min()), foff=abs(self.df),
            tsamp=self.dt, tstart=float(self.start_mjd),
            src_raj=_ra_str_to_sigproc(getattr(self, "ra_str", "")),
            src_dej=_dec_str_to_sigproc(getattr(self, "dec_str", "")),
            nifs=1, N=int(self.N))

    @property
    def nspectra(self) -> int:
        return int(self.N)

    @property
    def ptsperblk(self) -> int:
        """Spectra per block = spectra per subint (rfifind.c:214)."""
        return int(self.nsblk)

    def close(self):
        for f in self.files:
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- row geometry -------------------------------------------------
    def _row_start_spec_uncached(self, fi: int, row: int) -> int:
        """Absolute starting spectrum of (file, row), via OFFS_SUB when
        present (get_PSRFITS_rawblock, psrfits.c:690-705)."""
        m = self.meta[fi]
        sub = self.files[fi].hdu("SUBINT")
        tsub = self.dt * self.nsblk
        if self._offs_sub_zero:
            return m.start_spec + row * self.nsblk
        offs_sub = float(sub.read_col("OFFS_SUB", row)[0])
        rel = (offs_sub - (m.start_subint + 0.5) * tsub) / self.dt
        # snap to the row grid: the reference counts dropped blocks as
        # round(OFFS_SUB gap / TSUBINT) (psrfits.c:741-768), so
        # OFFS_SUB rounding drift (fractions of a row) must NOT
        # scatter rows off the nsblk grid and leave phantom pad gaps
        return m.start_spec + self.nsblk * int(round(rel / self.nsblk))

    def _row_start_spec(self, fi: int, row: int) -> int:
        if hasattr(self, "_row_specs"):
            return int(self._row_specs[fi][row])
        return self._row_start_spec_uncached(fi, row)

    # -- decoding -----------------------------------------------------
    def _pol_mode(self) -> int:
        """Polarization handling shared by the native and NumPy decode
        paths: >=0 select that pol, -2 sum the first two (AA+BB)."""
        if self.npol == 1:
            return 0
        sum_polns = (self.poln_order.startswith("AABB")
                     or self.npol == 2)
        if self.use_poln > 0 or (self.npol > 2 and not sum_polns):
            return max(self.use_poln - 1, 0)
        return -2

    def _decode_row_native(self, sub, raw: np.ndarray,
                           row: int) -> Optional[np.ndarray]:
        """Fused C++ subint decode (csrc/native_io.cpp pt_decode_subint);
        None when the native library or this geometry is unsupported
        (16/32-bit stays on the NumPy path).  Set `_use_native = False`
        on the instance to force the NumPy path (parity tests)."""
        if not getattr(self, "_use_native", True):
            return None
        if not native.can_decode_subint(self.npol, self.nchan,
                                        self.nbits):
            return None
        pol_mode = self._pol_mode()
        scl = offs = wts = None
        if self.apply_scale:
            scl = np.asarray(sub.read_col("DAT_SCL", row), np.float32)
        if self.apply_offset:
            offs = np.asarray(sub.read_col("DAT_OFFS", row), np.float32)
        if self.apply_weight:
            wts = np.asarray(sub.read_col("DAT_WTS", row), np.float32)
        return native.decode_subint(
            raw, self.nsblk, self.npol, self.nchan, self.nbits,
            self.zero_offset, scl, offs, wts, pol_mode, self.df < 0)

    def _decode_row(self, fi: int, row: int) -> np.ndarray:
        """One subint -> [nsblk, nchan] float32 (ascending freq)."""
        if self._cache_row[0] == (fi, row):
            return self._cache_row[1]
        sub = self.files[fi].hdu("SUBINT")
        raw = sub.read_col_raw_bytes("DATA", row)
        fast = self._decode_row_native(sub, raw, row)
        if fast is not None:
            fast = self._scrub_row(fast, fi, row)
            self._cache_row = ((fi, row), fast)
            return fast
        samples = unpack_samples(raw, self.nbits)
        nspec = self.nsblk
        data = np.asarray(samples, np.float32).reshape(
            nspec, self.npol, self.nchan)
        pol_mode = self._pol_mode()
        if self.npol > 1:
            if pol_mode >= 0:
                data = data[:, pol_mode:pol_mode + 1, :]
                polsl = slice(pol_mode * self.nchan,
                              (pol_mode + 1) * self.nchan)
            else:                              # -2: sum AA+BB
                data = data[:, :2, :]
                polsl = slice(0, 2 * self.nchan)
        else:
            polsl = slice(0, self.nchan)
        data = data - self.zero_offset
        if self.apply_scale or self.apply_offset:
            scl = np.ones(self.nchan * self.npol, np.float32)
            offs = np.zeros(self.nchan * self.npol, np.float32)
            if self.apply_scale:
                scl = np.asarray(sub.read_col("DAT_SCL", row),
                                 np.float32)
            if self.apply_offset:
                offs = np.asarray(sub.read_col("DAT_OFFS", row),
                                  np.float32)
            npol_used = data.shape[1]
            scl = scl[polsl].reshape(npol_used, self.nchan)
            offs = offs[polsl].reshape(npol_used, self.nchan)
            data = data * scl[None] + offs[None]
        if data.shape[1] > 1:
            data = data.sum(axis=1, keepdims=True)
        data = data[:, 0, :]
        if self.apply_weight:
            wts = np.asarray(sub.read_col("DAT_WTS", row), np.float32)
            data = data * wts[None, :]
        if self.df < 0:
            data = data[:, ::-1]      # present ascending
        out = np.ascontiguousarray(data, dtype=np.float32)
        out = self._scrub_row(out, fi, row)
        self._cache_row = ((fi, row), out)
        return out

    def _scrub_row(self, data: np.ndarray, fi: int,
                   row: int) -> np.ndarray:
        """Ingest quarantine on one decoded subint: NaN/Inf samples
        (32-bit data, or poisoned DAT_SCL/DAT_OFFS/DAT_WTS) scrub to
        0 and long zero-fill runs are recorded — both become mask
        entries downstream instead of exceptions or silent garbage."""
        if not self.quarantine:
            return data
        start = self._row_start_spec(fi, row)
        data = scrub_nonfinite(data, start, self.quality)
        record_zero_runs(data, start, self.quality)
        return data

    def read_spectra(self, start: int, count: int) -> np.ndarray:
        """[count, nchan] float32, ascending frequency; gaps (dropped
        rows, inter-file gaps, reads past EOF) fill with padvals."""
        out = np.empty((count, self.nchan), np.float32)
        out[:] = self.padvals[None, :]
        want_lo, want_hi = start, start + count
        for fi, m in enumerate(self.meta):
            specs = self._row_specs[fi]
            # only rows whose window can intersect [want_lo, want_hi)
            r0 = int(np.searchsorted(specs, want_lo - self.nsblk,
                                     side="right"))
            r1 = int(np.searchsorted(specs, want_hi, side="left"))
            for row in range(r0, r1):
                row_lo = int(specs[row])
                row_hi = row_lo + self.nsblk
                if row_hi <= want_lo or row_lo >= want_hi:
                    continue
                data = self._decode_row(fi, row)
                lo = max(row_lo, want_lo)
                hi = min(row_hi, want_hi)
                out[lo - start:hi - start] = data[lo - row_lo:hi - row_lo]
        return out

    def iter_blocks(self, block_size: int):
        for start in range(0, int(self.N), block_size):
            n = min(block_size, int(self.N) - start)
            yield start, self.read_spectra(start, n)


# ----------------------------------------------------------------------
# Synthesis (test corpus + converter source)
# ----------------------------------------------------------------------

def write_psrfits(path: str, data: np.ndarray, dt: float,
                  freqs: np.ndarray, nsblk: int = 256,
                  nbits: int = 8, npol: int = 1,
                  start_mjd: float = 55555.0,
                  scales: Optional[np.ndarray] = None,
                  offsets: Optional[np.ndarray] = None,
                  weights: Optional[np.ndarray] = None,
                  zero_off: float = 0.0,
                  drop_rows: Sequence[int] = (),
                  offs_jitter: float = 0.0,
                  src_name: str = "FAKE") -> None:
    """Write a SEARCH-mode PSRFITS file.

    data: [nspectra, nchan] float (will be quantized to nbits);
    freqs: [nchan] channel centers (MHz), ascending or descending;
    drop_rows: subint indices to OMIT (their OFFS_SUB gap simulates
    dropped blocks, the psrfits.c:741-768 test case);
    offs_jitter: deterministic alternating OFFS_SUB error in SAMPLES
    (real backends accumulate rounding drift; readers must snap to the
    row grid rather than see phantom gaps).
    """
    nspec, nchan = data.shape
    nsub = (nspec + nsblk - 1) // nsblk
    tsub = dt * nsblk
    if scales is None:
        scales = np.ones(nchan * npol, np.float32)
    if offsets is None:
        offsets = np.zeros(nchan * npol, np.float32)
    if weights is None:
        weights = np.ones(nchan, np.float32)

    nsamp_row = nsblk * npol * nchan
    rows = []
    for isub in range(nsub):
        if isub in drop_rows:
            continue
        chunk = np.zeros((nsblk, nchan), np.float32)
        have = data[isub * nsblk:(isub + 1) * nsblk]
        chunk[:len(have)] = have
        # invert the scaling the reader will apply
        q = (chunk - offsets[None, :nchan]) / \
            np.where(scales[None, :nchan] == 0, 1, scales[None, :nchan]) \
            + zero_off
        if nbits == 32:
            samples = q.astype(">f4").tobytes()
        elif nbits == 16:
            samples = np.clip(np.round(q), -32768,
                              32767).astype(">i2").tobytes()
        else:
            maxval = (1 << nbits) - 1
            qq = np.clip(np.round(q), 0, maxval).astype(np.uint8)
            if npol > 1:
                qq = np.repeat(qq[:, None, :], npol, axis=1)
            flat = qq.ravel()
            if nbits == 8:
                samples = flat.tobytes()
            elif nbits == 4:
                samples = ((flat[0::2] << 4) | flat[1::2]).tobytes()
            elif nbits == 2:
                samples = (flat[0::4] << 6 | flat[1::4] << 4
                           | flat[2::4] << 2 | flat[3::4]).tobytes()
            elif nbits == 1:
                samples = np.packbits(flat).tobytes()
            else:
                raise ValueError(nbits)
        jit = offs_jitter * dt * (1 if isub % 2 else -1)
        rows.append({
            "TSUBINT": np.float64(tsub),
            "OFFS_SUB": np.float64((isub + 0.5) * tsub + jit),
            "DAT_FREQ": np.asarray(freqs, np.float64),
            "DAT_WTS": np.asarray(weights, np.float32),
            "DAT_OFFS": np.asarray(offsets, np.float32),
            "DAT_SCL": np.asarray(scales, np.float32),
            "DATA": np.frombuffer(samples, np.uint8),
        })

    databytes = nsamp_row * nbits // 8
    imjd = int(start_mjd)
    smjd = int((start_mjd - imjd) * SECPERDAY)
    soffs = (start_mjd - imjd) * SECPERDAY - smjd
    primary = [
        ("OBS_MODE", "SEARCH"), ("TELESCOP", "FAKE_SCOPE"),
        ("OBSERVER", "presto_tpu"), ("SRC_NAME", src_name),
        ("FRONTEND", "synth"), ("BACKEND", "synth"),
        ("PROJID", "TEST"), ("DATE-OBS", "2020-01-01T00:00:00"),
        ("FD_POLN", "LIN"), ("RA", "00:00:00.0"),
        ("DEC", "00:00:00.0"),
        ("OBSFREQ", float(np.mean(freqs))),
        ("OBSNCHAN", nchan),
        ("OBSBW", float(freqs[-1] - freqs[0]) + 0.0),
        ("CHAN_DM", 0.0), ("BMIN", 0.1),
        ("STT_IMJD", imjd), ("STT_SMJD", smjd), ("STT_OFFS", soffs),
        ("TRK_MODE", "TRACK"),
    ]
    cards = [
        ("TBIN", dt), ("NCHAN", nchan), ("NPOL", npol),
        ("POL_TYPE", "AA+BB" if npol > 1 else "AA"),
        ("NCHNOFFS", 0), ("NSBLK", nsblk), ("NBITS", nbits),
        ("NSUBOFFS", 0), ("ZERO_OFF", zero_off),
    ]
    columns = [
        ("TSUBINT", "1D", "s"), ("OFFS_SUB", "1D", "s"),
        ("DAT_FREQ", "%dD" % nchan, "MHz"),
        ("DAT_WTS", "%dE" % nchan, ""),
        ("DAT_OFFS", "%dE" % (nchan * npol), ""),
        ("DAT_SCL", "%dE" % (nchan * npol), ""),
        ("DATA", "%dB" % databytes, "Jy"),
    ]
    write_fits(path, primary, [{
        "extname": "SUBINT", "cards": cards, "columns": columns,
        "rows": rows}])
