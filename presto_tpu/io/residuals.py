"""TEMPO resid2.tmp reader (lib/python/residuals.py analog).

resid2.tmp is a Fortran-unformatted file of 9-float64 (72-byte)
records: (bary TOA [MJD], postfit residual [pulse phase], postfit
residual [sec], orbital phase, bary obs freq [MHz], weight, timing
uncertainty [us], prefit residual [sec], ddm).  Each record is wrapped
in block markers whose width depends on the Fortran compiler; the
reference autodetects g77 (4-byte) vs gfortran (8-byte) markers
(src/barycenter.c read_resid_rec) — mirrored here.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

_RECLEN = 72


@dataclass
class Residuals:
    numTOAs: int = 0
    bary_TOA: np.ndarray = field(default_factory=lambda: np.zeros(0))
    postfit_phs: np.ndarray = field(default_factory=lambda: np.zeros(0))
    postfit_sec: np.ndarray = field(default_factory=lambda: np.zeros(0))
    orbit_phs: np.ndarray = field(default_factory=lambda: np.zeros(0))
    bary_freq: np.ndarray = field(default_factory=lambda: np.zeros(0))
    weight: np.ndarray = field(default_factory=lambda: np.zeros(0))
    uncertainty: np.ndarray = field(default_factory=lambda: np.zeros(0))
    prefit_phs: np.ndarray = field(default_factory=lambda: np.zeros(0))
    prefit_sec: np.ndarray = field(default_factory=lambda: np.zeros(0))
    ddm: np.ndarray = field(default_factory=lambda: np.zeros(0))


def _detect_marker(raw: bytes) -> int:
    """Marker width: the record marker holds the record length (72) as
    int32 (g77) or int64 (gfortran).  The low 4 bytes of a little-
    endian int64 72 also read as int32 72, so the TRAILING marker
    position disambiguates (the reference autodetects the same way,
    src/barycenter.c read_resid_rec)."""
    for m, fmt in ((4, "<i"), (8, "<q")):
        end = m + _RECLEN
        if (len(raw) >= end + m
                and struct.unpack(fmt, raw[:m])[0] == _RECLEN
                and struct.unpack(fmt, raw[end:end + m])[0] == _RECLEN):
            return m
    raise ValueError("not a resid2.tmp file (no Fortran record marker)")


def read_residuals(path: str) -> Residuals:
    with open(path, "rb") as f:
        raw = f.read()
    m = _detect_marker(raw)
    recsize = m + _RECLEN + m
    n = len(raw) // recsize
    rows = np.zeros((n, 9))
    for i in range(n):
        off = i * recsize
        rows[i] = np.frombuffer(raw[off + m:off + m + _RECLEN],
                                dtype="<f8")
    r = Residuals(numTOAs=n)
    r.bary_TOA = rows[:, 0]
    r.postfit_phs = rows[:, 1]
    r.postfit_sec = rows[:, 2]
    r.orbit_phs = rows[:, 3]
    r.bary_freq = rows[:, 4]
    r.weight = rows[:, 5]
    r.uncertainty = rows[:, 6]
    r.prefit_sec = rows[:, 7]
    r.ddm = rows[:, 8]
    # prefit residual in phase derived from sec via the TOA spacing is
    # not recoverable without the ephemeris; expose sec only
    r.prefit_phs = np.zeros(n)
    return r


def write_residuals(path: str, bary_TOA: np.ndarray,
                    postfit_phs: np.ndarray, postfit_sec: np.ndarray,
                    orbit_phs=None, bary_freq=None, weight=None,
                    uncertainty=None, prefit_sec=None, ddm=None,
                    marker: int = 4) -> None:
    """Write resid2.tmp (used for tests and for feeding tools that
    expect TEMPO output)."""
    n = len(bary_TOA)

    def arr(x):
        return np.zeros(n) if x is None else np.asarray(x, float)

    cols = [np.asarray(bary_TOA, float), np.asarray(postfit_phs, float),
            np.asarray(postfit_sec, float), arr(orbit_phs),
            arr(bary_freq), arr(weight), arr(uncertainty),
            arr(prefit_sec), arr(ddm)]
    fmt = "<i" if marker == 4 else "<q"
    with open(path, "wb") as f:
        for i in range(n):
            rec = b"".join(struct.pack("<d", c[i]) for c in cols)
            f.write(struct.pack(fmt, _RECLEN))
            f.write(rec)
            f.write(struct.pack(fmt, _RECLEN))
