"""2-D dynamic-spectra container (lib/python/spectra.py analog).

Holds [nchan, nspec] data + (freqs, dt, starttime) and offers the same
operations the reference class does: dedisperse (sample-shift, in
place), subband, downsample, trim, per-channel scaling, and masking —
NumPy/JAX-backed instead of loops.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from presto_tpu.ops.dedispersion import delay_from_dm


class Spectra:
    """data: [nchan, nspec] float32; freqs ascending or descending MHz
    (kept as given, like the reference)."""

    def __init__(self, freqs, dt: float, data, starttime: float = 0.0,
                 dm: float = 0.0):
        self.freqs = np.asarray(freqs, np.float64)
        self.dt = float(dt)
        self.data = np.asarray(data, np.float32)
        if self.data.shape[0] != self.freqs.size:
            raise ValueError("data rows != len(freqs)")
        self.starttime = float(starttime)
        self.dm = float(dm)

    @property
    def numchans(self) -> int:
        return self.data.shape[0]

    @property
    def numspectra(self) -> int:
        return self.data.shape[1]

    def get_chan(self, channum: int) -> np.ndarray:
        return self.data[channum]

    def shift_channels(self, bins, padval: float = 0.0) -> None:
        """Shift each channel left by bins[i] samples, pad the tail
        (spectra.py shift_channels semantics)."""
        bins = np.asarray(bins)
        n = self.numspectra
        for i in range(self.numchans):
            b = int(np.clip(bins[i], -n, n))   # |shift| >= n: all pad
            if b == 0:
                continue
            if b > 0:
                self.data[i, :n - b] = self.data[i, b:]
                self.data[i, n - b:] = padval
            else:
                self.data[i, -b:] = self.data[i, :n + b]
                self.data[i, :-b] = padval

    def dedisperse(self, dm: float, padval: float = 0.0,
                   ref_freq: Optional[float] = None) -> "Spectra":
        """In-place incoherent dedispersion to `dm` (relative to the
        current self.dm), referenced to ref_freq (default: highest)."""
        if ref_freq is None:
            ref_freq = self.freqs.max()
        ddm = dm - self.dm
        delays = (delay_from_dm(ddm, self.freqs)
                  - delay_from_dm(ddm, ref_freq))
        bins = np.round(np.asarray(delays) / self.dt).astype(int)
        self.shift_channels(bins, padval)
        self.dm = dm
        return self

    def subband(self, nsub: int, subdm: Optional[float] = None,
                padval: float = 0.0) -> "Spectra":
        """Average groups of channels into nsub subbands, optionally
        first aligning channels WITHIN each subband at subdm."""
        if self.numchans % nsub:
            raise ValueError("numchans must be divisible by nsub")
        if subdm is not None and subdm != self.dm:
            # align within subbands only: relative delay to each
            # subband's center frequency
            cps = self.numchans // nsub
            ddm = subdm - self.dm
            sub_ctr = self.freqs.reshape(nsub, cps).mean(axis=1)
            delays = delay_from_dm(ddm, self.freqs) \
                - np.repeat(np.asarray(delay_from_dm(ddm, sub_ctr)), cps)
            bins = np.round(np.asarray(delays) / self.dt).astype(int)
            self.shift_channels(bins, padval)
        cps = self.numchans // nsub
        newdata = self.data.reshape(nsub, cps, -1).mean(axis=1)
        newfreqs = self.freqs.reshape(nsub, cps).mean(axis=1)
        return Spectra(newfreqs, self.dt, newdata, self.starttime,
                       self.dm)

    def downsample(self, factor: int) -> "Spectra":
        keep = (self.numspectra // factor) * factor
        nd = self.data[:, :keep].reshape(
            self.numchans, -1, factor).mean(axis=2)
        return Spectra(self.freqs, self.dt * factor, nd,
                       self.starttime, self.dm)

    def trim(self, start: int, stop: int) -> "Spectra":
        return Spectra(self.freqs, self.dt, self.data[:, start:stop],
                       self.starttime + start * self.dt, self.dm)

    def scaled(self, indep: bool = False) -> "Spectra":
        """Mean-0 channels; indep=True also scales each channel to
        unit std (spectra.py scaled/scaled2)."""
        d = self.data - self.data.mean(axis=1, keepdims=True)
        if indep:
            std = d.std(axis=1, keepdims=True)
            d = d / np.where(std == 0, 1.0, std)
        return Spectra(self.freqs, self.dt, d, self.starttime, self.dm)

    def mask_channels(self, channums: Sequence[int],
                      maskval: float = 0.0) -> None:
        self.data[list(channums), :] = maskval

    def scrub(self, padval: float = 0.0) -> int:
        """Ingest quarantine for in-memory spectra: replace NaN/Inf
        samples (corrupt blocks that slipped past the readers, or
        downstream math on masked data) with `padval` in place.
        Returns the number of samples scrubbed so callers can log or
        add the count to a DataQualityReport."""
        bad = ~np.isfinite(self.data)
        nbad = int(bad.sum())
        if nbad:
            self.data[bad] = padval
        return nbad

    def mean_spectrum(self) -> np.ndarray:
        return self.data.mean(axis=1)

    def timeseries(self) -> np.ndarray:
        """Band-summed series at the current DM."""
        return self.data.sum(axis=0)
