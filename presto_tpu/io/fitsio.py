"""Minimal FITS reader/writer (primary HDU + binary tables).

astropy/CFITSIO are not available in this environment, and the
reference's own pure-Python PSRFITS reader (lib/python/psrfits.py)
proves a small purpose-built reader suffices.  This module implements
just the FITS subset PSRFITS search-mode data uses:
  - 2880-byte logical blocks of 80-char header cards
  - primary HDU with no data
  - BINTABLE extensions (BITPIX=8) with TFORM codes
    L/B/X/I/J/K/E/D/A including repeat counts
Row data is exposed lazily as numpy arrays; column reads slice the
row-record memory-map, so reading one column of one row never touches
the rest of the file.
"""

from __future__ import annotations

import mmap
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.io.errors import PrestoIOError

BLOCK = 2880
CARD = 80

# TFORM letter -> (numpy dtype (big-endian), bytes per element)
_TFORM_DTYPES = {
    "L": (np.dtype("u1"), 1),
    "B": (np.dtype("u1"), 1),
    "X": (np.dtype("u1"), 1),          # bit array: repeat counts BITS
    "I": (np.dtype(">i2"), 2),
    "J": (np.dtype(">i4"), 4),
    "K": (np.dtype(">i8"), 8),
    "E": (np.dtype(">f4"), 4),
    "D": (np.dtype(">f8"), 8),
    "A": (np.dtype("S1"), 1),
}


def _fmt_card(key: str, value, comment: str = "") -> bytes:
    """Format one 80-byte header card."""
    if key in ("COMMENT", "HISTORY", "END"):
        return ("%-8s%s" % (key, value))[:CARD].ljust(CARD).encode()
    if isinstance(value, bool):
        vstr = "T" if value else "F"
        card = "%-8s= %20s" % (key, vstr)
    elif isinstance(value, (int, np.integer)):
        card = "%-8s= %20d" % (key, value)
    elif isinstance(value, (float, np.floating)):
        card = "%-8s= %20s" % (key, repr(float(value)))
    else:
        card = "%-8s= %-20s" % (key, "'%s'" % str(value))
    if comment:
        card += " / " + comment
    return card[:CARD].ljust(CARD).encode()


def _parse_value(raw: str):
    v = raw.strip()
    if not v:
        return None
    if v.startswith("'"):
        end = v.rfind("'")
        return v[1:end].rstrip()
    if v == "T":
        return True
    if v == "F":
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v.replace("D", "E").replace("d", "e"))
    except ValueError:
        return v


@dataclass
class Header:
    cards: Dict[str, Any] = field(default_factory=dict)

    def get(self, key, default=None):
        return self.cards.get(key, default)

    def __getitem__(self, key):
        return self.cards[key]

    def __contains__(self, key):
        return key in self.cards

    def __setitem__(self, key, value):
        self.cards[key] = value


def _read_header(buf, offset: int, path: str = "") -> Tuple[Header, int]:
    """Parse header cards from `offset`; returns (header, data_offset)."""
    hdr = Header()
    pos = offset
    done = False
    while not done:
        block = buf[pos:pos + BLOCK]
        if len(block) < BLOCK:
            raise PrestoIOError("truncated FITS header", path=path,
                                offset=pos, expected_bytes=BLOCK,
                                actual_bytes=len(block),
                                kind="truncated-header")
        for i in range(0, BLOCK, CARD):
            card = block[i:i + CARD].decode("ascii", "replace")
            key = card[:8].strip()
            if key == "END":
                done = True
                break
            if not key or key in ("COMMENT", "HISTORY"):
                continue
            if card[8:10] == "= ":
                body = card[10:]
                slash = _find_comment_slash(body)
                hdr.cards[key] = _parse_value(
                    body[:slash] if slash >= 0 else body)
        pos += BLOCK
    return hdr, pos


def _find_comment_slash(body: str) -> int:
    """Index of the comment '/', respecting quoted strings."""
    inq = False
    for i, ch in enumerate(body):
        if ch == "'":
            inq = not inq
        elif ch == "/" and not inq:
            return i
    return -1


@dataclass
class Column:
    name: str
    code: str          # TFORM letter
    repeat: int        # element count (bits for X)
    offset: int        # byte offset within the row record
    nbytes: int
    unit: str = ""

    @property
    def dtype(self):
        return _TFORM_DTYPES[self.code][0]


@dataclass
class BinTableHDU:
    header: Header
    columns: List[Column]
    data_offset: int
    naxis1: int        # row record bytes
    naxis2: int        # rows
    _buf: Any = None
    path: str = ""

    def colindex(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def _check(self, start: int, nbytes: int, name: str) -> None:
        """Bounds-check a column read against the actual file size —
        a table whose NAXIS2 promises more rows than the file holds
        (truncated download, killed writer) must fail with a typed
        error, not a numpy buffer exception."""
        avail = len(self._buf) - start
        if start < 0 or avail < nbytes:
            raise PrestoIOError(
                "truncated FITS table data (column %s)" % name,
                path=self.path, offset=start, expected_bytes=nbytes,
                actual_bytes=max(0, avail), kind="truncated-data")

    def read_col(self, name: str, row: int,
                 count: Optional[int] = None) -> np.ndarray:
        """Read one row's worth of column `name` (0-based row)."""
        c = self.colindex(name)
        start = self.data_offset + row * self.naxis1 + c.offset
        if c.code == "X":
            nbytes = (c.repeat + 7) // 8
            self._check(start, nbytes, name)
            raw = np.frombuffer(self._buf, np.uint8, nbytes, start)
            return raw
        n = count if count is not None else c.repeat
        elem = _TFORM_DTYPES[c.code][1]
        self._check(start, n * elem, name)
        raw = np.frombuffer(self._buf, c.dtype, n, start)
        if c.code == "A":
            return raw
        return raw.astype(c.dtype.newbyteorder("="))

    def read_col_raw_bytes(self, name: str, row: int) -> np.ndarray:
        """The undecoded bytes of column `name` for one row."""
        c = self.colindex(name)
        start = self.data_offset + row * self.naxis1 + c.offset
        self._check(start, c.nbytes, name)
        return np.frombuffer(self._buf, np.uint8, c.nbytes, start)


def _parse_bintable(hdr: Header, data_offset: int, buf,
                    path: str = "") -> BinTableHDU:
    tfields = int(hdr["TFIELDS"])
    cols = []
    off = 0
    for i in range(1, tfields + 1):
        tform = str(hdr["TFORM%d" % i]).strip()
        j = 0
        while j < len(tform) and tform[j].isdigit():
            j += 1
        repeat = int(tform[:j]) if j else 1
        code = tform[j] if j < len(tform) else "A"
        if code not in _TFORM_DTYPES:
            raise ValueError("unsupported TFORM %r" % tform)
        if code == "X":
            nbytes = (repeat + 7) // 8
        else:
            nbytes = repeat * _TFORM_DTYPES[code][1]
        cols.append(Column(name=str(hdr.get("TTYPE%d" % i, "COL%d" % i)
                                    ).strip(),
                           code=code, repeat=repeat, offset=off,
                           nbytes=nbytes,
                           unit=str(hdr.get("TUNIT%d" % i, "")).strip()))
        off += nbytes
    naxis1 = int(hdr["NAXIS1"])
    if off > naxis1:
        raise PrestoIOError("FITS columns overflow NAXIS1 (%d > %d)"
                            % (off, naxis1), path=path,
                            kind="bad-header")
    return BinTableHDU(header=hdr, columns=cols, data_offset=data_offset,
                       naxis1=naxis1, naxis2=int(hdr["NAXIS2"]),
                       _buf=buf, path=path)


class FitsFile:
    """Read-only FITS file: primary header + list of HDUs."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        try:
            try:
                self._mm = mmap.mmap(self._f.fileno(), 0,
                                     access=mmap.ACCESS_READ)
            except (ValueError, OSError):
                self._mm = self._f.read()
            self.primary, pos = _read_header(self._mm, 0, path)
            if self.primary.get("NAXIS", 0) not in (0, None):
                # skip primary data if any
                nax = int(self.primary["NAXIS"])
                if nax > 0:
                    n = abs(int(self.primary["BITPIX"])) // 8
                    for a in range(1, nax + 1):
                        n *= int(self.primary["NAXIS%d" % a])
                    pos += (n + BLOCK - 1) // BLOCK * BLOCK
            self.hdus: List[BinTableHDU] = []
            size = len(self._mm)
            while pos < size:
                hdr, doff = _read_header(self._mm, pos, path)
                if str(hdr.get("XTENSION", "")).strip() != "BINTABLE":
                    raise ValueError(
                        "only BINTABLE extensions supported")
                hdu = _parse_bintable(hdr, doff, self._mm, path)
                self.hdus.append(hdu)
                nbytes = hdu.naxis1 * hdu.naxis2
                pos = doff + (nbytes + BLOCK - 1) // BLOCK * BLOCK
        except KeyError as e:
            # a required card (TFIELDS/NAXIS1/...) vanished: typed
            # corruption error, not a KeyError escape
            self.close()
            raise PrestoIOError("missing FITS card %s" % e, path=path,
                                kind="bad-header") from None
        except BaseException:
            self.close()
            raise

    def hdu(self, extname: str) -> BinTableHDU:
        for h in self.hdus:
            if str(h.header.get("EXTNAME", "")).strip() == extname:
                return h
        raise KeyError(extname)

    def close(self):
        if getattr(self, "_mm", None) is not None \
                and isinstance(self._mm, mmap.mmap):
            self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ----------------------------------------------------------------------
# Writing (for synthesis of test corpora and converters)
# ----------------------------------------------------------------------

def _pad_block(b: bytes, fill: bytes = b" ") -> bytes:
    rem = len(b) % BLOCK
    return b if rem == 0 else b + fill * (BLOCK - rem)


def write_fits(path: str, primary_cards: Sequence[Tuple],
               tables: Sequence[Dict]) -> None:
    """Write a FITS file.

    primary_cards: [(key, value, comment)] for the primary HDU.
    tables: each {"extname", "cards": [(k,v,c)], "columns":
    [(name, tform, unit)], "rows": [ {colname: ndarray/scalar} ]}.
    """
    out = bytearray()
    cards = [_fmt_card("SIMPLE", True), _fmt_card("BITPIX", 8),
             _fmt_card("NAXIS", 0), _fmt_card("EXTEND", True)]
    for kvc in primary_cards:
        k, v = kvc[0], kvc[1]
        c = kvc[2] if len(kvc) > 2 else ""
        cards.append(_fmt_card(k, v, c))
    cards.append(_fmt_card("END", ""))
    out += _pad_block(b"".join(cards))

    for tab in tables:
        colspecs = tab["columns"]
        # compute row layout
        offsets, off = [], 0
        dts = []
        for name, tform, *_ in colspecs:
            j = 0
            while j < len(tform) and tform[j].isdigit():
                j += 1
            repeat = int(tform[:j]) if j else 1
            code = tform[j]
            nbytes = ((repeat + 7) // 8 if code == "X"
                      else repeat * _TFORM_DTYPES[code][1])
            offsets.append(off)
            dts.append((code, repeat, nbytes))
            off += nbytes
        naxis1 = off
        rows = tab["rows"]
        cards = [_fmt_card("XTENSION", "BINTABLE"),
                 _fmt_card("BITPIX", 8), _fmt_card("NAXIS", 2),
                 _fmt_card("NAXIS1", naxis1),
                 _fmt_card("NAXIS2", len(rows)),
                 _fmt_card("PCOUNT", 0), _fmt_card("GCOUNT", 1),
                 _fmt_card("TFIELDS", len(colspecs))]
        for i, (name, tform, *rest) in enumerate(colspecs, 1):
            cards.append(_fmt_card("TTYPE%d" % i, name))
            cards.append(_fmt_card("TFORM%d" % i, tform))
            if rest and rest[0]:
                cards.append(_fmt_card("TUNIT%d" % i, rest[0]))
        cards.append(_fmt_card("EXTNAME", tab["extname"]))
        for kvc in tab.get("cards", []):
            k, v = kvc[0], kvc[1]
            c = kvc[2] if len(kvc) > 2 else ""
            cards.append(_fmt_card(k, v, c))
        cards.append(_fmt_card("END", ""))
        out += _pad_block(b"".join(cards))

        data = bytearray()
        for row in rows:
            rec = bytearray(naxis1)
            for (name, tform, *_), offset, (code, repeat, nbytes) \
                    in zip(colspecs, offsets, dts):
                val = row[name]
                if code == "A":
                    s = str(val).encode()[:repeat].ljust(repeat)
                    rec[offset:offset + repeat] = s
                elif code == "X":
                    raw = np.asarray(val, np.uint8).tobytes()[:nbytes]
                    rec[offset:offset + len(raw)] = raw
                else:
                    dt = _TFORM_DTYPES[code][0]
                    arr = np.asarray(val, dtype=dt.newbyteorder("=")) \
                        .astype(dt).ravel()
                    raw = arr.tobytes()[:nbytes].ljust(nbytes, b"\0")
                    rec[offset:offset + nbytes] = raw
            data += rec
        out += _pad_block(bytes(data), fill=b"\0")

    from presto_tpu.io.atomic import atomic_write_bytes
    atomic_write_bytes(path, bytes(out))
