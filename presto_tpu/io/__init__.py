"""io layer: raw-data readers, artifact writers, fault-tolerance
primitives.

Robustness conventions (docs/ROBUSTNESS.md): artifact writers are
atomic (io/atomic.py), readers quarantine recoverable damage into a
DataQualityReport (io/quality.py) and raise the typed PrestoIOError
(io/errors.py) for genuinely unrecoverable corruption.
"""

from presto_tpu.io.errors import PrestoIOError  # noqa: F401
