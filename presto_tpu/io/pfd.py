""".pfd (prepfold data) and .bestprof artifacts.

Binary layout parity with the reference's writer (prepfold.c delayed
write) as documented by its pure-Python reader
(lib/python/prepfold.py:17-150): little-endian —
  12 x i32: numdms numperiods numpdots nsub npart proflen numchan
            pstep pdstep dmstep ndmfact npfact
  4 length-prefixed strings: filenm candnm telescope pgdev
  2 x 16-byte char: rastr decstr (must contain ':')
  9 x f64: dt startT endT tepoch bepoch avgvoverc lofreq chan_wid bestdm
  3 x (f32 pow, f32 pad, 3 x f64 p1 p2 p3): topo, bary, fold
     (NOTE: fold values are frequencies f, fd, fdd)
  7 x f64 orbit params (p e x w t pd wd)
  f64 arrays: dms[numdms] periods[numperiods] pdots[numpdots]
  f64 profs [npart][nsub][proflen]
  7 x f64 foldstats per (part, sub): numdata data_avg data_var numprof
     prof_avg prof_var redchi
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np


def _wstr(f, s: str) -> None:
    b = s.encode()
    f.write(struct.pack("<i", len(b)))
    f.write(b)


def _rstr(f, path: str = "") -> str:
    from presto_tpu.io.errors import PrestoIOError, read_exact
    n = struct.unpack("<i", read_exact(f, 4, path,
                                       "pfd string length"))[0]
    if n < 0 or n > 1 << 20:
        raise PrestoIOError("implausible pfd string length %d" % n,
                            path=path, offset=f.tell() - 4,
                            kind="bad-magic")
    return read_exact(f, n, path, "pfd string").decode()


@dataclass
class Pfd:
    """In-memory .pfd contents (field names follow the reference's
    Python pfd class for drop-in familiarity)."""
    numdms: int = 1
    numperiods: int = 1
    numpdots: int = 1
    nsub: int = 1
    npart: int = 1
    proflen: int = 64
    numchan: int = 1
    pstep: int = 1
    pdstep: int = 2
    dmstep: int = 1
    ndmfact: int = 2
    npfact: int = 1
    filenm: str = ""
    candnm: str = ""
    telescope: str = "Unknown"
    pgdev: str = ""
    rastr: str = "00:00:00.0000"
    decstr: str = "00:00:00.0000"
    dt: float = 0.0
    startT: float = 0.0
    endT: float = 1.0
    tepoch: float = 0.0
    bepoch: float = 0.0
    avgvoverc: float = 0.0
    lofreq: float = 0.0
    chan_wid: float = 0.0
    bestdm: float = 0.0
    topo_pow: float = 0.0
    topo_p1: float = 0.0
    topo_p2: float = 0.0
    topo_p3: float = 0.0
    bary_pow: float = 0.0
    bary_p1: float = 0.0
    bary_p2: float = 0.0
    bary_p3: float = 0.0
    fold_pow: float = 0.0
    fold_p1: float = 0.0     # frequencies!
    fold_p2: float = 0.0
    fold_p3: float = 0.0
    orb_p: float = 0.0
    orb_e: float = 0.0
    orb_x: float = 0.0
    orb_w: float = 0.0
    orb_t: float = 0.0
    orb_pd: float = 0.0
    orb_wd: float = 0.0
    dms: np.ndarray = field(default_factory=lambda: np.zeros(1))
    periods: np.ndarray = field(default_factory=lambda: np.zeros(1))
    pdots: np.ndarray = field(default_factory=lambda: np.zeros(1))
    profs: np.ndarray = field(
        default_factory=lambda: np.zeros((1, 1, 64)))
    stats: np.ndarray = field(
        default_factory=lambda: np.zeros((1, 1, 7)))


def pfd_subfreqs(p: Pfd) -> np.ndarray:
    """Subband center frequencies (MHz), ascending: lofreq is the
    CENTER of the lowest channel (infodata convention, makeinf.h)."""
    chan_per_sub = max(p.numchan // max(p.nsub, 1), 1)
    sub_bw = chan_per_sub * p.chan_wid
    lo_edge = p.lofreq - 0.5 * p.chan_wid
    return lo_edge + (np.arange(p.nsub) + 0.5) * sub_bw


def write_pfd(path: str, p: Pfd) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack("<5i", p.numdms, p.numperiods, p.numpdots,
                            p.nsub, p.npart))
        f.write(struct.pack("<7i", p.proflen, p.numchan, p.pstep,
                            p.pdstep, p.dmstep, p.ndmfact, p.npfact))
        for s in (p.filenm, p.candnm, p.telescope, p.pgdev):
            _wstr(f, s)
        for s in (p.rastr, p.decstr):
            b = s.encode()[:15]
            f.write(b + b"\0" * (16 - len(b)))
        f.write(struct.pack("<2d", p.dt, p.startT))
        f.write(struct.pack("<7d", p.endT, p.tepoch, p.bepoch,
                            p.avgvoverc, p.lofreq, p.chan_wid, p.bestdm))
        for pow_, p1, p2, p3 in ((p.topo_pow, p.topo_p1, p.topo_p2,
                                  p.topo_p3),
                                 (p.bary_pow, p.bary_p1, p.bary_p2,
                                  p.bary_p3),
                                 (p.fold_pow, p.fold_p1, p.fold_p2,
                                  p.fold_p3)):
            f.write(struct.pack("<2f", pow_, 0.0))
            f.write(struct.pack("<3d", p1, p2, p3))
        f.write(struct.pack("<7d", p.orb_p, p.orb_e, p.orb_x, p.orb_w,
                            p.orb_t, p.orb_pd, p.orb_wd))
        np.asarray(p.dms, "<f8").tofile(f)
        np.asarray(p.periods, "<f8").tofile(f)
        np.asarray(p.pdots, "<f8").tofile(f)
        np.ascontiguousarray(p.profs, "<f8").tofile(f)
        np.ascontiguousarray(p.stats, "<f8").tofile(f)


def read_pfd(path: str) -> Pfd:
    """Parse one .pfd.  Missing or truncated input raises the typed
    PrestoIOError (path + byte-offset context) instead of a bare
    FileNotFoundError / struct.error escape — a discovery-DAG timing
    node fed a corrupt fold fails terminal with a diagnosable event,
    not a stack trace into the struct module."""
    from presto_tpu.io.errors import PrestoIOError, read_exact
    p = Pfd()
    try:
        f = open(path, "rb")
    except OSError as e:
        raise PrestoIOError("cannot open .pfd: %s" % e.strerror,
                            path=path, kind="missing") from None
    with f:
        (p.numdms, p.numperiods, p.numpdots, p.nsub,
         p.npart) = struct.unpack(
            "<5i", read_exact(f, 20, path, "pfd header"))
        (p.proflen, p.numchan, p.pstep, p.pdstep, p.dmstep, p.ndmfact,
         p.npfact) = struct.unpack(
            "<7i", read_exact(f, 28, path, "pfd header"))
        p.filenm, p.candnm = _rstr(f, path), _rstr(f, path)
        p.telescope, p.pgdev = _rstr(f, path), _rstr(f, path)
        p.rastr = read_exact(f, 16, path,
                             "pfd header").split(b"\0")[0].decode()
        p.decstr = read_exact(f, 16, path,
                              "pfd header").split(b"\0")[0].decode()
        p.dt, p.startT = struct.unpack(
            "<2d", read_exact(f, 16, path, "pfd header"))
        (p.endT, p.tepoch, p.bepoch, p.avgvoverc, p.lofreq, p.chan_wid,
         p.bestdm) = struct.unpack(
            "<7d", read_exact(f, 56, path, "pfd header"))
        for pre in ("topo", "bary", "fold"):
            pow_, _ = struct.unpack(
                "<2f", read_exact(f, 8, path, "pfd header"))
            p1, p2, p3 = struct.unpack(
                "<3d", read_exact(f, 24, path, "pfd header"))
            setattr(p, pre + "_pow", pow_)
            setattr(p, pre + "_p1", p1)
            setattr(p, pre + "_p2", p2)
            setattr(p, pre + "_p3", p3)
        (p.orb_p, p.orb_e, p.orb_x, p.orb_w, p.orb_t, p.orb_pd,
         p.orb_wd) = struct.unpack(
            "<7d", read_exact(f, 56, path, "pfd header"))

        def _farr(n, what):
            arr = np.frombuffer(
                read_exact(f, 8 * n, path, what), "<f8")
            return arr.copy()

        p.dms = _farr(p.numdms, "pfd dms")
        p.periods = _farr(p.numperiods, "pfd periods")
        p.pdots = _farr(p.numpdots, "pfd pdots")
        n = p.npart * p.nsub * p.proflen
        if n <= 0 or n > (1 << 28):
            raise PrestoIOError(
                "implausible pfd cube %d x %d x %d"
                % (p.npart, p.nsub, p.proflen), path=path,
                kind="bad-magic")
        p.profs = _farr(n, "pfd profile cube").reshape(
            p.npart, p.nsub, p.proflen)
        p.stats = _farr(p.npart * p.nsub * 7, "pfd foldstats").reshape(
            p.npart, p.nsub, 7)
    return p


def write_bestprof(path: str, p: Pfd, best_prof: np.ndarray,
                   best_p: float, best_pd: float, best_redchi: float,
                   perr: float = 0.0, pderr: float = 0.0,
                   datnm: str = "", candnm: str = "") -> None:
    """Text .bestprof (format of lib/python/bestprof.py's parser)."""
    N = float(p.stats[:, 0, 0].sum())
    data_avg = float(np.average(p.stats[:, :, 1]))
    data_std = float(np.sqrt(np.average(p.stats[:, :, 2])))
    prof_avg = float(best_prof.mean())
    prof_std = float(best_prof.std())
    with open(path, "w") as f:
        w = f.write
        w("# Input file       =  %s\n" % (datnm or p.filenm))
        w("# Candidate        =  %s\n" % (candnm or p.candnm or
                                          "PSR_CAND"))
        w("# Telescope        =  %s\n" % p.telescope)
        w("# Epoch_topo       =  %.15g\n" % p.tepoch)
        w("# Epoch_bary (MJD) =  %.15g\n" % p.bepoch)
        w("# T_sample         =  %g\n" % p.dt)
        w("# Data Folded      =  %d\n" % N)
        w("# Data Avg         =  %.6g\n" % data_avg)
        w("# Data StdDev      =  %.6g\n" % data_std)
        w("# Profile Bins     =  %d\n" % p.proflen)
        w("# Profile Avg      =  %.6g\n" % prof_avg)
        w("# Profile StdDev   =  %.6g\n" % prof_std)
        w("# Reduced chi-sqr  =  %.4f\n" % best_redchi)
        w("# Best DM          =  %.6f\n" % p.bestdm)
        w("# P_topo (ms)      =  %.12g +/- %.3g\n"
          % (best_p * 1000.0, perr * 1000.0))
        w("# P'_topo (s/s)    =  %.6g +/- %.3g\n" % (best_pd, pderr))
        w("######################################################\n")
        for i, v in enumerate(best_prof):
            w("%4d  %.7g\n" % (i, v))


def use_for_timing(p: Pfd) -> bool:
    """True when the fold can produce valid TOAs: the best (searched)
    solution must agree with the FOLD solution to within a 0.1-bin
    rotation over the observation, else prepfold's search moved the
    profile and TOAs from it are bogus (prepfold.py:325-346).
    """
    from presto_tpu.utils.psr import p_to_f
    T = p.dt * float(p.stats[:, 0, 0].sum())
    # best-solution choice mirrors freq_offsets (prepfold.py:250-266):
    # barycentric fold (fold_pow == 1) compares against the bary
    # values; an un-searched topocentric fold (topo_p1 == 0) has zero
    # offsets by construction
    if p.fold_pow == 1.0:
        best = (p.bary_p1, p.bary_p2, p.bary_p3)
    elif p.topo_p1 == 0.0:
        return True
    else:
        best = (p.topo_p1, p.topo_p2, p.topo_p3)
    if not best[0]:
        return False
    f3 = p_to_f(*best)
    offs = np.abs(np.asarray(f3) -
                  np.asarray([p.fold_p1, p.fold_p2, p.fold_p3]))
    dphi = offs * np.asarray([T, T ** 2 / 2.0, T ** 3 / 6.0])
    return bool(dphi.max() <= 0.1 / p.proflen)
