"""SIGPROC filterbank (.fil) reader/writer.

Format parity: reference src/sigproc_fb.c — length-prefixed keyword
strings between HEADER_START/HEADER_END, little-endian binary values
(write_filterbank_header sigproc_fb.c:191-226, read_filterbank_header
sigproc_fb.c:229-336).  Data: nsamples × nifs × nchans samples of
nbits each, time-major, typically descending frequency (foff < 0).

This module is pure Python/NumPy host code; bit-unpacking for 1/2/4-bit
data has both a NumPy path and (when built) a C++ fast path
(presto_tpu.native).
"""

from __future__ import annotations

import io
import os
import struct
from dataclasses import dataclass
from typing import BinaryIO, Iterator

import numpy as np

from presto_tpu.io import native
from presto_tpu.io.errors import PrestoIOError, read_exact
from presto_tpu.io.quality import (DataQualityReport, record_zero_runs,
                                   scrub_nonfinite)

_TELESCOPES = {0: "Fake", 1: "Arecibo", 2: "Ooty", 3: "Nancay", 4: "Parkes",
               5: "Jodrell", 6: "GBT", 7: "GMRT", 8: "Effelsberg"}

_INT_KEYS = {"machine_id", "telescope_id", "data_type", "nchans", "nbits",
             "nifs", "nbeams", "ibeam", "barycentric", "pulsarcentric",
             "nsamples"}
_DBL_KEYS = {"az_start", "za_start", "src_raj", "src_dej", "tstart", "tsamp",
             "fch1", "foff", "refdm", "period"}
_STR_KEYS = {"rawdatafile", "source_name"}


def _send_string(f: BinaryIO, s: str) -> None:
    b = s.encode()
    f.write(struct.pack("<i", len(b)))
    f.write(b)


def _send_int(f: BinaryIO, name: str, val: int) -> None:
    _send_string(f, name)
    f.write(struct.pack("<i", int(val)))


def _send_double(f: BinaryIO, name: str, val: float) -> None:
    _send_string(f, name)
    f.write(struct.pack("<d", float(val)))


def _get_string(f: BinaryIO, path: str = "") -> str:
    nbytes = struct.unpack(
        "<i", read_exact(f, 4, path, "SIGPROC header"))[0]
    if not 0 < nbytes < 200:
        raise ValueError("bad SIGPROC header string length %d" % nbytes)
    return read_exact(f, nbytes, path, "SIGPROC header").decode()


@dataclass
class FilterbankHeader:
    """Header of a SIGPROC filterbank file (sigproc_fb.c sigprocfb)."""
    source_name: str = "fake"
    rawdatafile: str = ""
    machine_id: int = 10
    telescope_id: int = 0
    data_type: int = 1
    fch1: float = 0.0          # MHz, center freq of FIRST (highest) channel
    foff: float = 0.0          # MHz, channel offset (negative: descending)
    nchans: int = 0
    nbits: int = 8
    tstart: float = 0.0        # MJD
    tsamp: float = 0.0         # seconds
    nifs: int = 1
    nbeams: int = 1
    ibeam: int = 1
    src_raj: float = 0.0       # hhmmss.s
    src_dej: float = 0.0       # ddmmss.s
    az_start: float = 0.0
    za_start: float = 0.0
    headerlen: int = 0         # filled in by read
    N: int = 0                 # samples in file, filled in by read

    @property
    def band_ascending(self) -> bool:
        return self.foff > 0

    @property
    def lofreq(self) -> float:
        """Center frequency of the lowest channel, MHz."""
        if self.foff < 0:
            return self.fch1 + (self.nchans - 1) * self.foff
        return self.fch1

    @property
    def bytes_per_spectrum(self) -> int:
        return self.nchans * self.nifs * self.nbits // 8


def write_filterbank_header(hdr: FilterbankHeader, f: BinaryIO) -> None:
    """Parity: write_filterbank_header (sigproc_fb.c:191-226)."""
    _send_string(f, "HEADER_START")
    if hdr.rawdatafile:
        _send_string(f, "rawdatafile")
        _send_string(f, hdr.rawdatafile)
    if hdr.source_name:
        _send_string(f, "source_name")
        _send_string(f, hdr.source_name)
    _send_int(f, "machine_id", hdr.machine_id)
    _send_int(f, "telescope_id", hdr.telescope_id)
    _send_double(f, "src_raj", hdr.src_raj)
    _send_double(f, "src_dej", hdr.src_dej)
    _send_double(f, "az_start", hdr.az_start)
    _send_double(f, "za_start", hdr.za_start)
    _send_int(f, "data_type", 1)
    _send_double(f, "fch1", hdr.fch1)
    _send_double(f, "foff", hdr.foff)
    _send_int(f, "nchans", hdr.nchans)
    _send_int(f, "nbits", hdr.nbits)
    _send_double(f, "tstart", hdr.tstart)
    _send_double(f, "tsamp", hdr.tsamp)
    _send_int(f, "nifs", hdr.nifs)
    _send_string(f, "HEADER_END")


def read_filterbank_header(f: BinaryIO,
                           path: str = "") -> FilterbankHeader:
    """Parity: read_filterbank_header (sigproc_fb.c:229-336).

    Truncated headers raise a typed PrestoIOError (file, offset,
    expected/actual bytes) instead of a bare struct.error escape.
    """
    hdr = FilterbankHeader()
    first = _get_string(f, path)
    if first != "HEADER_START":
        raise ValueError("not a SIGPROC filterbank file")
    while True:
        key = _get_string(f, path)
        if key == "HEADER_END":
            break
        if key in _INT_KEYS:
            val = struct.unpack(
                "<i", read_exact(f, 4, path, "SIGPROC header"))[0]
            if key == "nsamples":
                continue
            if hasattr(hdr, key):
                setattr(hdr, key, val)
        elif key in _DBL_KEYS:
            val = struct.unpack(
                "<d", read_exact(f, 8, path, "SIGPROC header"))[0]
            if hasattr(hdr, key):
                setattr(hdr, key, val)
        elif key in _STR_KEYS:
            setattr(hdr, key, _get_string(f, path))
        else:
            raise ValueError("unknown SIGPROC header key: %r" % key)
    hdr.headerlen = f.tell()
    if hdr.nchans <= 0 or hdr.nifs <= 0 or hdr.nbits <= 0:
        # corrupt header values would divide by zero below / poison
        # every downstream geometry computation
        raise PrestoIOError(
            "invalid SIGPROC geometry (nchans=%d nifs=%d nbits=%d)"
            % (hdr.nchans, hdr.nifs, hdr.nbits), path=path,
            kind="bad-header")
    try:
        pos = f.tell()
        f.seek(0, os.SEEK_END)
        filelen = f.tell()
        f.seek(pos)
        hdr.N = (filelen - hdr.headerlen) * 8 \
            // (hdr.nbits * hdr.nchans * hdr.nifs)
    except (OSError, io.UnsupportedOperation):
        # unseekable stream (live socket/pipe feed): the observation
        # length is unknown until EOF — N stays 0 and the streaming
        # consumer accounts spectra as they arrive
        hdr.N = 0
    return hdr


def unpack_bits(raw: np.ndarray, nbits: int) -> np.ndarray:
    """Unpack 1/2/4-bit samples from a uint8 array; passthrough for >=8.

    Bit order parity: PRESTO unpacks most-significant-first within each
    byte (psrfits.c:828-866 convention).
    """
    if nbits == 8:
        return raw
    if nbits == 16:
        return raw.view(np.uint16)
    if nbits == 32:
        return raw.view(np.float32)
    if nbits == 4:
        out = np.empty(raw.size * 2, dtype=np.uint8)
        out[0::2] = raw >> 4
        out[1::2] = raw & 0x0F
        return out
    if nbits == 2:
        out = np.empty(raw.size * 4, dtype=np.uint8)
        for i, shift in enumerate((6, 4, 2, 0)):
            out[i::4] = (raw >> shift) & 0x03
        return out
    if nbits == 1:
        out = np.unpackbits(raw.reshape(-1, 1), axis=1, bitorder="big")
        return out.reshape(-1)
    raise ValueError("unsupported nbits=%d" % nbits)


def pack_bits(data: np.ndarray, nbits: int) -> np.ndarray:
    """Inverse of unpack_bits for writing packed .fil files."""
    if nbits == 8:
        return data.astype(np.uint8)
    if nbits == 16:
        return data.astype(np.uint16).view(np.uint8)
    if nbits == 32:
        return data.astype(np.float32).view(np.uint8)
    d = data.astype(np.uint8)
    if nbits == 4:
        return ((d[0::2] << 4) | (d[1::2] & 0x0F)).astype(np.uint8)
    if nbits == 2:
        out = np.zeros(d.size // 4, dtype=np.uint8)
        for i, shift in enumerate((6, 4, 2, 0)):
            out |= (d[i::4] & 0x03) << shift
        return out
    if nbits == 1:
        return np.packbits(d.reshape(-1, 8), axis=1, bitorder="big").ravel()
    raise ValueError("unsupported nbits=%d" % nbits)


def decode_spectra_block(hdr: FilterbankHeader, raw: np.ndarray,
                         nspec: int) -> np.ndarray:
    """Packed filterbank bytes -> [nspec, nchans] float32, channels in
    ASCENDING frequency order.  The one decode sequence shared by the
    file reader, the prefetched feeder path, and the live socket /
    file-tail producers (presto_tpu/stream/source.py): native decoder
    when available, numpy unpack + IF-sum + descending-band flip
    otherwise."""
    arr = native.decode_spectra(raw, nspec, hdr.nifs, hdr.nchans,
                                hdr.nbits, hdr.foff < 0)
    if arr is None:
        vals = unpack_bits(raw, hdr.nbits)
        arr = vals.astype(np.float32).reshape(nspec, hdr.nifs,
                                              hdr.nchans)
        arr = arr.sum(axis=1) if hdr.nifs > 1 else arr[:, 0, :]
        if hdr.foff < 0:
            arr = np.ascontiguousarray(arr[:, ::-1])
    return arr


class FilterbankFile:
    """A SIGPROC .fil file with block reads in channel-ascending order.

    read_spectra() returns float32 [nsamp, nchans] with channels in
    ASCENDING frequency order (flipping if foff < 0), the order the
    dedispersion ops expect — the reference does the same flip inside
    its readers (get_filterbank_rawblock, sigproc_fb.c:419-).
    """

    def __init__(self, path: str, quarantine: bool = True):
        self.path = path
        self.f = open(path, "rb")
        try:
            self.header = read_filterbank_header(self.f, path)
        except PrestoIOError:
            # already typed (truncated header): keep file/offset info
            self.f.close()
            raise
        except (ValueError, struct.error) as e:
            self.f.close()
            raise ValueError("%s is not a SIGPROC filterbank file (%s)"
                             % (path, e)) from None
        self.quarantine = quarantine
        self.quality = DataQualityReport(path=path,
                                         nspectra=self.header.N,
                                         nchan=self.header.nchans)

    def close(self):
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def nspectra(self) -> int:
        return self.header.N

    @property
    def ptsperblk(self) -> int:
        """Spectra per "block" for interval sizing (rfifind -blocks).

        SIGPROC filterbanks are flat streams with no native block
        structure; the reference adopts 2400 spectra as the blocksize
        (sigproc_fb.c:388).
        """
        return 2400

    def read_spectra(self, start: int, count: int) -> np.ndarray:
        """Read `count` spectra starting at `start`; zero-pad past EOF.

        Short reads (the file shrank after open — a writer died or the
        volume went away) are quarantined: the missing tail is recorded
        in self.quality and zero-filled rather than crashing in the
        decoder's reshape.
        """
        hdr = self.header
        bps = hdr.bytes_per_spectrum
        self.f.seek(hdr.headerlen + start * bps)
        navail = max(0, min(count, hdr.N - start))
        raw = np.frombuffer(self.f.read(navail * bps), dtype=np.uint8)
        got = len(raw) // bps
        if got < navail:
            raw = raw[:got * bps]
            self.quality.add(start + got, start + navail, "short-read")
        arr = self._decode_raw(raw, got)
        arr = self._scrub(arr, start, got)
        if got < count:
            pad = np.zeros((count - got, hdr.nchans), dtype=np.float32)
            arr = np.concatenate([arr, pad], axis=0)
        return np.ascontiguousarray(arr)

    def _scrub(self, arr: np.ndarray, start: int,
               nspec: int) -> np.ndarray:
        """Ingest quarantine on a decoded block: NaN/Inf samples are
        scrubbed to 0 (only 32-bit data can hold them) and long
        zero-fill runs recorded; both land in self.quality for the
        mask integration downstream."""
        if not self.quarantine or nspec == 0:
            return arr
        if self.header.nbits == 32:
            arr = scrub_nonfinite(arr, start, self.quality)
        record_zero_runs(arr[:nspec], start, self.quality)
        return arr

    def _decode_raw(self, raw: np.ndarray, nspec: int) -> np.ndarray:
        """Packed bytes -> [nspec, nchans] float32 ascending (the ONE
        decode sequence shared by the random-access and prefetched
        read paths)."""
        return decode_spectra_block(self.header, raw, nspec)

    def iter_blocks(self, block_size: int,
                    start: int = 0) -> Iterator[np.ndarray]:
        pos = start
        while pos < self.header.N:
            yield self.read_spectra(pos, block_size)
            pos += block_size

    def stream_blocks(self, block_size: int,
                      start: int = 0) -> Iterator[np.ndarray]:
        """Sequential [block_size, nchans] float32 blocks (zero-padded
        final block), read through the native prefetching feeder when
        available so disk IO overlaps the consumer's compute (the
        INSTRUMENTOBJS double-buffer role, csrc/native_io.cpp).
        Falls back to iter_blocks semantics otherwise."""
        hdr = self.header
        bps = hdr.bytes_per_spectrum
        if not (native.available() and hdr.nbits in (1, 2, 4, 8)
                and (hdr.nifs * hdr.nchans * hdr.nbits) % 8 == 0):
            yield from self.iter_blocks(block_size, start)
            return
        feeder = native.BlockFeeder(self.path,
                                    hdr.headerlen + start * bps,
                                    block_size * bps, nbuf=4)
        try:
            delivered = 0
            total = hdr.N - start
            for raw in feeder:
                nspec = min(len(raw) // bps, total - delivered)
                if nspec <= 0:
                    break
                arr = self._decode_raw(raw[:nspec * bps], nspec)
                arr = self._scrub(arr, start + delivered, nspec)
                if nspec < block_size:
                    arr = np.concatenate(
                        [arr, np.zeros((block_size - nspec,
                                        hdr.nchans), np.float32)])
                delivered += nspec
                yield arr
        finally:
            feeder.close()



class FilterbankSet:
    """Multiple .fil files presented as one time-contiguous observation
    (the reference reads multi-file observations the same way: all
    readers take N files and stitch them — read_filterbank_files,
    sigproc_fb.c:338; the multifiles virtual-file idea, multifiles.c).

    Files are ordered by start MJD; headers must agree on nchans/tsamp/
    foff/nbits.  Gaps between files are NOT padded (the reference pads
    via start_spec bookkeeping; synthesized multi-file sets here are
    contiguous) — a gap raises unless tolerance allows it.
    """

    def __init__(self, paths):
        if isinstance(paths, str):
            paths = [paths]
        self.files = [FilterbankFile(p) for p in paths]
        self.files.sort(key=lambda fb: fb.header.tstart)
        h0 = self.files[0].header
        for fb in self.files[1:]:
            h = fb.header
            if (h.nchans != h0.nchans or h.nbits != h0.nbits
                    or abs(h.tsamp - h0.tsamp) > 1e-12
                    or abs(h.foff - h0.foff) > 1e-9):
                raise ValueError("filterbank files disagree: %s vs %s"
                                 % (fb.path, self.files[0].path))
        import copy
        self.header = copy.copy(h0)
        self.header.N = sum(fb.header.N for fb in self.files)
        self.path = self.files[0].path
        # absolute starting spectrum of each file within the set
        self._starts = np.cumsum(
            [0] + [fb.header.N for fb in self.files[:-1]])

    @property
    def quality(self) -> DataQualityReport:
        """Merged member-file quarantine ledgers, shifted to the
        stitched observation's spectrum indices."""
        out = DataQualityReport(path=self.path,
                                nspectra=int(self.header.N),
                                nchan=self.header.nchans)
        for fb, start in zip(self.files, self._starts):
            out.scrubbed_samples += fb.quality.scrubbed_samples
            for iv in fb.quality.intervals:
                out.add(iv.start + int(start), iv.stop + int(start),
                        iv.reason)
        return out

    def close(self):
        for fb in self.files:
            fb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def nspectra(self) -> int:
        return self.header.N

    @property
    def ptsperblk(self) -> int:
        return 2400              # see FilterbankFile.ptsperblk

    def read_spectra(self, start: int, count: int) -> np.ndarray:
        out = np.zeros((count, self.header.nchans), dtype=np.float32)
        got = 0
        while got < count:
            pos = start + got
            i = int(np.searchsorted(self._starts, pos, side="right")) - 1
            if i >= len(self.files):
                break
            fb = self.files[i]
            local = pos - int(self._starts[i])
            if local >= fb.header.N:
                break             # past the last file: stay zero-padded
            n = min(count - got, fb.header.N - local)
            out[got:got + n] = fb.read_spectra(local, n)
            got += n
        return out

    def iter_blocks(self, block_size: int,
                    start: int = 0) -> Iterator[np.ndarray]:
        pos = start
        while pos < self.header.N:
            yield self.read_spectra(pos, block_size)
            pos += block_size


def write_filterbank(path: str, hdr: FilterbankHeader,
                     data: np.ndarray) -> None:
    """Write [nsamp, nchans] data (ascending freq) to a .fil file.

    If hdr.foff < 0 the channel axis is flipped to descending order on
    disk, matching standard SIGPROC convention.
    """
    from presto_tpu.io.atomic import atomic_open
    arr = data
    if hdr.foff < 0:
        arr = arr[:, ::-1]
    with atomic_open(path, "wb") as f:
        write_filterbank_header(hdr, f)
        packed = pack_bits(np.ascontiguousarray(arr).ravel(), hdr.nbits)
        f.write(packed.tobytes())
