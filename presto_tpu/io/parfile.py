"""TEMPO/TEMPO2 .par pulsar-ephemeris parser.

Parity targets: lib/python/parfile.py (psr_par class) and
src/readpar.c (get_psr_from_parfile).  Key-value lines with optional
fit-flag and error columns, Fortran 'D' exponents, P<->F derivation,
ELL1 (EPS1/EPS2/TASC) -> (E/OM/T0) conversion, and OrbitParams export
for the folding/search tools.
"""

from __future__ import annotations

import math
import re
from typing import Optional

from presto_tpu.astro.bary import parse_ra, parse_dec
from presto_tpu.ops.orbit import OrbitParams

SECPERDAY = 86400.0
TWOPI = 2.0 * math.pi

# parameter classes (parfile.py:48-57)
FLOAT_KEYS = {
    "PEPOCH", "POSEPOCH", "DM", "START", "FINISH", "NTOA", "TRES",
    "TZRMJD", "TZRFRQ", "NITS", "A1", "XDOT", "E", "ECC", "EDOT",
    "T0", "PB", "PBDOT", "OM", "OMDOT", "EPS1", "EPS2", "EPS1DOT",
    "EPS2DOT", "TASC", "LAMBDA", "BETA", "RA_RAD", "DEC_RAD", "GAMMA",
    "SINI", "M2", "MTOT", "XPBDOT", "ELAT", "ELONG", "PMLAMBDA",
    "PMBETA", "PX", "PMRA", "PMDEC", "PB_2", "A1_2", "E_2", "T0_2",
    "OM_2", "DMEPOCH",
}
FLOATN_PREFIXES = ("F", "P", "FB", "FD", "DMX_", "DMXEP_", "DMXR1_",
                   "DMXR2_", "DMXF1_", "DMXF2_")
# legacy bare spin keys ('P  0.714519' old-style pars) -> numbered form
LEGACY_ALIASES = {"P": "P0", "PD": "P1", "F": "F0", "FD": "F1"}
STR_KEYS = {"FILE", "PSR", "PSRJ", "PSRB", "EPHEM", "CLK", "BINARY",
            "RAJ", "DECJ", "UNITS", "TZRSITE"}


class Parfile:
    """Parsed .par file: parameters become attributes (self.F0,
    self.RAJ, ...), errors get an _ERR suffix.  Mirrors psr_par."""

    def __init__(self, parfilenm: str):
        self.FILE = parfilenm
        with open(parfilenm) as pf:
            for line in pf:
                self._parse_line(line)
        self._derive()

    # -- parsing ---------------------------------------------------- #

    def _parse_line(self, line: str) -> None:
        if line.startswith("#"):
            return
        line = line.replace("D-", "E-").replace("D+", "E+")
        parts = line.split()
        if not parts:
            return
        key = LEGACY_ALIASES.get(parts[0], parts[0])
        if key in STR_KEYS:
            setattr(self, key, parts[1])
        elif key in FLOAT_KEYS or self._is_floatn(key):
            try:
                setattr(self, key, float(parts[1]))
            except (ValueError, IndexError):
                return
        else:
            return
        # trailing columns: [fitflag] error  (parfile.py:104-109)
        if len(parts) == 3 and parts[2] not in ("0", "1"):
            try:
                setattr(self, key + "_ERR", float(parts[2]))
            except ValueError:
                pass
        elif len(parts) == 4:
            try:
                setattr(self, key + "_ERR", float(parts[3]))
            except ValueError:
                pass

    @staticmethod
    def _is_floatn(key: str) -> bool:
        """Numbered-family params: F0/F1/..., P0, FB0, FD1, DMX_0021
        (parfile.py:55-56 floatn_keys + regex at :75-77)."""
        m = re.match(r"^([A-Z]+_?)\d+$", key)
        return bool(m) and m.group(1) in FLOATN_PREFIXES

    # -- derived quantities (parfile.py:110-181) --------------------- #

    def _derive(self) -> None:
        if hasattr(self, "P0") and not hasattr(self, "F0"):
            self.F0 = 1.0 / self.P0
        if hasattr(self, "F0") and not hasattr(self, "P0"):
            self.P0 = 1.0 / self.F0
        if hasattr(self, "FB0") and not hasattr(self, "PB"):
            self.PB = (1.0 / self.FB0) / SECPERDAY
        if hasattr(self, "P1") and not hasattr(self, "F1"):
            self.F1 = -self.P1 / (self.P0 * self.P0)
        if hasattr(self, "F1") and not hasattr(self, "P1"):
            self.P1 = -self.F1 / (self.F0 * self.F0)
        if hasattr(self, "F2") and not hasattr(self, "P2") \
                and hasattr(self, "F0"):
            f0, f1, f2 = self.F0, getattr(self, "F1", 0.0), self.F2
            self.P2 = (2.0 * f1 * f1 / f0 - f2) / (f0 * f0)
        if hasattr(self, "RAJ"):
            self.RA_RAD = parse_ra(self.RAJ)
        if hasattr(self, "DECJ"):
            self.DEC_RAD = parse_dec(self.DECJ)
        if hasattr(self, "EPS1") and hasattr(self, "EPS2"):
            from presto_tpu.ops.orbit import ell1_to_keplerian
            tasc = getattr(self, "TASC", 0.0)
            pb = getattr(self, "PB", 0.0)
            self.E, self.OM, t0 = ell1_to_keplerian(
                self.EPS1, self.EPS2, tasc, pb)
            if hasattr(self, "TASC") and hasattr(self, "PB"):
                self.T0 = t0
        if hasattr(self, "ECC") and not hasattr(self, "E"):
            self.E = self.ECC
        if hasattr(self, "PB") and hasattr(self, "A1") \
                and not hasattr(self, "E"):
            self.E = 0.0
        if hasattr(self, "T0") and not hasattr(self, "TASC") \
                and hasattr(self, "PB") and hasattr(self, "OM"):
            self.TASC = self.T0 - self.PB * self.OM / 360.0
        if hasattr(self, "T0") and not hasattr(self, "OM"):
            self.OM = 0.0

    # -- exports ---------------------------------------------------- #

    @property
    def name(self) -> str:
        return getattr(self, "PSRJ",
                       getattr(self, "PSR", getattr(self, "PSRB", "")))

    @property
    def is_binary(self) -> bool:
        return hasattr(self, "PB") and hasattr(self, "A1")

    def orbit(self, epoch: Optional[float] = None) -> Optional[OrbitParams]:
        """OrbitParams with p in seconds and (when epoch given) t set
        to seconds since the last periastron before `epoch` (MJD) —
        the convention psrepoch/fold expect (database.c:203-213)."""
        if not self.is_binary:
            return None
        p_sec = self.PB * SECPERDAY
        # PBDOT convention: literal values (e.g. '-2.423E-12') pass
        # through; bare TEMPO-style values ('-2.423') are in 1e-12
        # units (psr_par's |PBDOT|>1e-7 heuristic)
        pbdot = getattr(self, "PBDOT", 0.0)
        if abs(pbdot) > 1e-7:
            pbdot *= 1e-12
        orb = OrbitParams(p=p_sec, x=self.A1, e=getattr(self, "E", 0.0),
                          w=getattr(self, "OM", 0.0), pd=pbdot,
                          wd=getattr(self, "OMDOT", 0.0))
        if epoch is not None and hasattr(self, "T0"):
            t = SECPERDAY * (epoch - self.T0)
            orb.t = t % p_sec
        else:
            orb.t = getattr(self, "T0", 0.0)   # MJD until epoch applied
        return orb

    def spin_at(self, epoch: float):
        """(f, fd, fdd) advanced from PEPOCH to `epoch` (MJD)."""
        f0 = getattr(self, "F0", 0.0)
        f1 = getattr(self, "F1", 0.0)
        f2 = getattr(self, "F2", 0.0)
        dt = (epoch - getattr(self, "PEPOCH", epoch)) * SECPERDAY
        return (f0 + f1 * dt + 0.5 * f2 * dt * dt, f1 + f2 * dt, f2)

    def __str__(self) -> str:
        out = []
        for k, v in sorted(self.__dict__.items()):
            if isinstance(v, str):
                out.append("%10s = '%s'" % (k, v))
            else:
                out.append("%10s = %-20.15g" % (k, v))
        return "\n".join(out) + "\n"


def read_parfile(path: str) -> Parfile:
    return Parfile(path)
