"""ctypes bindings for the C++ native IO runtime (csrc/native_io.cpp).

The reference keeps its raw-data path in C (INSTRUMENTOBJS: bit-unpack
psrfits.c:828-866, scale/offset/weight psrfits.c:805-814, the
get_rawblock readers behind backend_common.h:86-87).  This module loads
the TPU-era equivalent — fused decode kernels + a pthread prefetching
block feeder — and silently falls back to pure NumPy when the shared
library is absent or `PRESTO_TPU_NO_NATIVE=1`.

The library is auto-built with `make -C csrc` on first import when a
compiler is available; every entry point here is exercised against the
NumPy reference path in tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libpresto_tpu_io.so")

_lib = None
_load_failed = False


def _try_build() -> None:
    src = os.path.join(_CSRC, "native_io.cpp")
    if not os.path.exists(src):
        return
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(src)):
        return
    try:
        subprocess.run(["make", "-C", _CSRC], check=True,
                       capture_output=True, timeout=120)
    except Exception:
        pass


def _load():
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("PRESTO_TPU_NO_NATIVE"):
        return None
    _try_build()
    if not os.path.exists(_SO):
        _load_failed = True      # don't re-spawn make per decode call
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _load_failed = True
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64 = ctypes.c_int64
    i32 = ctypes.c_int
    lib.pt_unpack_bits.argtypes = [u8p, i64, i32, u8p]
    lib.pt_unpack_to_float.argtypes = [u8p, i64, i32, f32p]
    lib.pt_decode_spectra.argtypes = [u8p, i64, i32, i32, i32, i32, f32p]
    lib.pt_decode_subint.argtypes = [u8p, i64, i32, i32, i32,
                                     ctypes.c_float, f32p, f32p, f32p,
                                     i32, i32, f32p]
    lib.pt_feeder_open.argtypes = [ctypes.c_char_p, i64, i64, i32]
    lib.pt_feeder_open.restype = ctypes.c_void_p
    lib.pt_feeder_next.argtypes = [ctypes.c_void_p, u8p]
    lib.pt_feeder_next.restype = i64
    lib.pt_feeder_close.argtypes = [ctypes.c_void_p]
    try:
        # added after the first shipped .so: a stale library without
        # the symbol still serves every older entry point
        lib.pt_feeder_stats.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(i64)]
    except AttributeError:
        pass
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f32ptr(a: Optional[np.ndarray]):
    if a is None:
        return None
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def unpack_bits(raw: np.ndarray, nbits: int) -> Optional[np.ndarray]:
    """1/2/4-bit -> uint8, MSB-first. None if native path unavailable."""
    lib = _load()
    if lib is None or nbits not in (1, 2, 4, 8):
        return None
    raw = np.ascontiguousarray(raw, np.uint8)
    out = np.empty(raw.size * 8 // nbits, np.uint8)
    lib.pt_unpack_bits(_u8ptr(raw), raw.size, nbits, _u8ptr(out))
    return out


def decode_spectra(raw: np.ndarray, nspec: int, nifs: int, nchan: int,
                   nbits: int, flip: bool) -> Optional[np.ndarray]:
    """Fused filterbank block decode -> float32 [nspec, nchan]."""
    lib = _load()
    if lib is None or nbits not in (1, 2, 4, 8):
        return None
    raw = np.ascontiguousarray(raw, np.uint8)
    if raw.size * 8 != nspec * nifs * nchan * nbits:
        return None
    if (nifs * nchan * nbits) % 8 != 0:
        return None      # spectra not byte-aligned; NumPy path handles
    out = np.empty((nspec, nchan), np.float32)
    lib.pt_decode_spectra(_u8ptr(raw), nspec, nifs, nchan, nbits,
                          int(flip), _f32ptr(out))
    return out


def can_decode_subint(npol: int, nchan: int, nbits: int) -> bool:
    """Cheap predicate: native decode_subint supports this geometry.
    Lets callers skip gathering scale/offset/weight columns when the
    NumPy fallback would be used anyway."""
    return (_load() is not None and nbits in (1, 2, 4, 8)
            and (npol * nchan * nbits) % 8 == 0)


def decode_subint(raw: np.ndarray, nspec: int, npol: int, nchan: int,
                  nbits: int, zero_off: float,
                  scl: Optional[np.ndarray], offs: Optional[np.ndarray],
                  wts: Optional[np.ndarray], pol_mode: int,
                  flip: bool) -> Optional[np.ndarray]:
    """Fused PSRFITS subint decode (psrfits.c:789-920 analog).

    pol_mode: >=0 select that pol, -2 sum the first two pols.
    scl/offs are [npol*nchan]; wts is [nchan]; any may be None.
    """
    lib = _load()
    if lib is None or nbits not in (1, 2, 4, 8):
        return None
    raw = np.ascontiguousarray(raw, np.uint8)
    if raw.size * 8 != nspec * npol * nchan * nbits:
        return None
    if (npol * nchan * nbits) % 8 != 0:
        return None      # spectra not byte-aligned; NumPy path handles
    scl = None if scl is None else np.ascontiguousarray(scl, np.float32)
    offs = None if offs is None else np.ascontiguousarray(offs, np.float32)
    wts = None if wts is None else np.ascontiguousarray(wts, np.float32)
    # C reads scl/offs[0:npol*nchan] and wts[0:nchan]: short arrays
    # (malformed TFORM repeat counts) must fall back to the NumPy path,
    # which raises loudly instead of reading out of bounds
    if any(a is not None and a.size < npol * nchan for a in (scl, offs)):
        return None
    if wts is not None and wts.size < nchan:
        return None
    out = np.empty((nspec, nchan), np.float32)
    lib.pt_decode_subint(_u8ptr(raw), nspec, npol, nchan, nbits,
                         float(zero_off), _f32ptr(scl), _f32ptr(offs),
                         _f32ptr(wts), pol_mode, int(flip), _f32ptr(out))
    return out


class BlockFeeder:
    """Background-prefetching sequential block reader over one file.

    Wraps the pthread ring-buffer feeder: the read of block k+1..k+nbuf
    overlaps the consumer's processing of block k, hiding disk latency
    from the device-feed loop (the role the reference's streaming
    double-buffer plays, prepsubband.c:930-942).
    """

    def __init__(self, path: str, start_offset: int, block_bytes: int,
                 nbuf: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        self.block_bytes = int(block_bytes)
        self._h = lib.pt_feeder_open(path.encode(), int(start_offset),
                                     self.block_bytes, int(nbuf))
        if not self._h:
            raise OSError("pt_feeder_open failed for %s" % path)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            buf = np.empty(self.block_bytes, np.uint8)
            n = self._lib.pt_feeder_next(self._h, _u8ptr(buf))
            if n < 0:
                raise IOError("I/O error while prefetching blocks")
            if n == 0:
                return
            yield buf[:n]

    def stats(self) -> Optional[dict]:
        """Ingest-overlap attribution: blocks delivered plus how often
        each side of the ring waited on the other (consumer_waits ->
        disk-bound, producer_waits -> compute-bound).  None when the
        loaded library predates the symbol."""
        if not self._h or not hasattr(self._lib, "pt_feeder_stats"):
            return None
        out = (ctypes.c_int64 * 3)()
        self._lib.pt_feeder_stats(self._h, out)
        return {"blocks": int(out[0]),
                "consumer_waits": int(out[1]),
                "producer_waits": int(out[2])}

    def close(self) -> None:
        if self._h:
            self._lib.pt_feeder_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
