"""presto-lint: AST-driven invariant analysis for the presto_tpu tree.

The repo's hardest-won correctness properties — crash-atomic artifact
writes (`io/atomic.py`), epoch-fenced ledger commits
(`pipeline/leaseledger.py`), lock-guarded replica state
(`serve/fleet.py`), and the byte-identity contract of jitted stages
(PAPER.md) — were each proven by construction once and then guarded
only by chaos tests that *sample* the failure space.  This package
encodes them as machine-checked rules instead, so a future PR cannot
silently regress them: every check family walks the real source ASTs
and fails tier-1 with exact ``file:line`` findings.

Check families (see docs/LINTING.md for the catalog):

  atomic-write      artifact writers in pipeline/ serve/ obs/ must go
                    through io.atomic.atomic_open or a recognized
                    tmp+os.replace / fence-staged idiom
  fence-discipline  ledger-owned state mutates only inside the
                    fence-checked commit paths
  lock-guard        attributes declared guarded are only touched with
                    their lock held
  lock-order        the lock-acquisition graph across serve/ is acyclic
  trace-purity      functions reachable from jit/pjit/pallas entry
                    points never call time/random/host-I/O
  import-hygiene    no unused or duplicate imports (the in-tree twin
                    of the pyproject ruff config)
  obs-coverage      the 14 instrumentation-coverage checks formerly in
                    tools/obs_lint.py (thin shim kept there)

Use `run_lint()` for the full suite, or `core.run_checks()` for a
subset over an arbitrary (possibly in-memory) tree.
"""

from presto_tpu.lint.core import (  # noqa: F401  (public API)
    Finding,
    Tree,
    apply_baseline,
    baseline_entry,
    load_baseline,
    registered_checks,
    run_checks,
    save_baseline,
)

# importing the check modules registers them
from presto_tpu.lint import atomicwrite  # noqa: F401
from presto_tpu.lint import fence        # noqa: F401
from presto_tpu.lint import locks        # noqa: F401
from presto_tpu.lint import purity       # noqa: F401
from presto_tpu.lint import imports      # noqa: F401
from presto_tpu.lint import obscoverage  # noqa: F401


def run_lint(root, baseline_path=None, checks=None):
    """Run every registered family over the repo at `root`, applying
    the committed baseline.  Returns (findings, suppressed, stale):
    `findings` must be empty for the tree to pass, `stale` lists
    baseline entries that no longer match anything (they fail too, so
    the baseline shrinks monotonically)."""
    tree = Tree.collect(root)
    findings = run_checks(tree, checks=checks)
    baseline = load_baseline(baseline_path) if baseline_path else []
    return apply_baseline(tree, findings, baseline)
