"""fence-discipline: ledger-owned state mutates only through the
fence-checked commit paths.

The whole zombie-safety story of the elastic and fleet layers
(docs/ROBUSTNESS.md) rests on one funnel: every mutation of
ledger-owned state — the ledger row files (``shards.json`` /
``jobs.json`` / ``items.json``), per-host heartbeat files (``.hb-*``),
and fence-landed results (``result.json``) — happens inside
`pipeline/leaseledger.py` (or its two subclass modules), under the
ledger lock, behind the epoch fence.  A direct write from ``serve/``
or ``tools/`` would land state the fence never examined: a dead
replica's late output could overwrite a journaled artifact, or a
monitoring script could flip a row no epoch bump protects.

Two patterns are flagged outside the ledger modules:

1. calls into the ledger's private transaction API (``._save`` /
   ``._load`` / ``._commit_row`` / ``._readmit`` / ``._items`` /
   ``._fence_why`` / ``._reject_stale``) on any receiver whose
   expression mentions "ledger" — the public methods (lease /
   complete / fail / reap / ...) are the only supported surface;
2. write calls (``open(..., "w"/"wb")``, ``atomic_write_text`` /
   ``atomic_write_bytes``, ``os.replace`` / ``os.rename``) whose
   arguments contain a ledger-owned filename — renaming something
   onto ``result.json`` yourself is exactly the zombie write the
   fence exists to reject.

Read-only access (``ledger.read()``, opening the files with the
default mode) is deliberately out of scope: monitoring tools may look,
they may not touch.
"""

from __future__ import annotations

import ast
from typing import List

from presto_tpu.lint.core import (Finding, Tree, call_name,
                                  const_strings, dotted_name,
                                  register, str_const)

CHECK = "fence-discipline"

#: the fence-checked commit paths themselves
LEDGER_MODULES = (
    "presto_tpu/pipeline/leaseledger.py",
    "presto_tpu/pipeline/shardledger.py",
    "presto_tpu/serve/jobledger.py",
    "presto_tpu/serve/federation.py",
)

#: where direct mutations would be reachable from
SCOPES = ("presto_tpu/serve/", "presto_tpu/pipeline/", "tools/")

PRIVATE_API = {"_save", "_load", "_commit_row", "_readmit",
               "_items", "_fence_why", "_reject_stale"}

#: filename markers of ledger-owned state; the triage weights file is
#: owned by presto_tpu/triage/model.py (schema-versioned, atomic,
#: defensive load) — a direct write from serve// pipeline// tools/
#: would be exactly the poisoned-model path ROBUSTNESS.md rules out
OWNED_MARKERS = ("jobs.json", "shards.json", "items.json",
                 "result.json", ".hb-", "fleets.json",
                 "triage_weights.json")

WRITE_CALLS = {"atomic_write_text", "atomic_write_bytes",
               "os.replace", "os.rename"}
WRITE_MODES = ("w", "wb", "w+", "wb+", "wt")


def _is_write_call(call: ast.Call) -> bool:
    name = call_name(call)
    if name in WRITE_CALLS:
        return True
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in ("atomic_write_text",
                                   "atomic_write_bytes"):
        return True
    if name in ("open", "os.fdopen", "fdopen"):
        mode = None
        if len(call.args) >= 2:
            mode = str_const(call.args[1])
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = str_const(kw.value)
        return mode in WRITE_MODES
    return False


@register(CHECK)
def check(tree: Tree) -> List[Finding]:
    out: List[Finding] = []
    for sf in tree.under(*SCOPES):
        if sf.path in LEDGER_MODULES or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            # 1. private ledger transaction API from outside
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in PRIVATE_API:
                recv = dotted_name(node.func.value) or ""
                if "ledger" in recv.lower():
                    out.append(Finding(
                        CHECK, sf.path, node.lineno,
                        "call to private ledger API %s.%s() outside "
                        "the fence-checked commit paths — only the "
                        "public lease/complete/fail/reap surface "
                        "keeps the epoch fence between a zombie and "
                        "the journal"
                        % (recv, node.func.attr)))
                continue
            # 2. direct writes to ledger-owned files
            if _is_write_call(node):
                hit = [m for m in OWNED_MARKERS
                       if any(m in s
                              for a in list(node.args)
                              + [k.value for k in node.keywords]
                              for s in const_strings(a))]
                if hit:
                    out.append(Finding(
                        CHECK, sf.path, node.lineno,
                        "direct write touching ledger-owned file "
                        "%r — ledger state lands only through the "
                        "fence-checked commit transaction "
                        "(pipeline/leaseledger.py)" % hit[0]))
    return out
