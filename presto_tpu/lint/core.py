"""presto-lint framework: parsed-source tree, check registry, pragma
suppression, and the committed-baseline protocol.

Design choices that matter:

* **One parse per file.**  `Tree` walks the scan roots once, parses
  every ``.py`` into an AST, and hands the same `SourceFile` objects
  to every check — a check is a pure function `Tree -> [Finding]`.
* **Pragmas are positional.**  ``# presto-lint: allow(check-a,
  check-b)`` on the finding's line (or the line directly above it)
  suppresses those families at that line only — a blanket opt-out
  does not exist by design.
* **The baseline is for grandfathered sites.**  Entries match on
  (check, path, stripped source line), not on line numbers, so code
  motion does not resurrect them; an entry that matches nothing is
  *stale* and itself fails the run — the baseline can only shrink.
* **In-memory trees.**  `Tree.from_sources` builds the same structure
  from literal strings, which is how the test suite proves each check
  fires on a synthetic violation without committing bad code.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(r"#\s*presto-lint:\s*allow\(([^)]*)\)")


@dataclass(frozen=True)
class Finding:
    """One check violation, anchored to a source line."""
    check: str          # check family id, e.g. "atomic-write"
    path: str           # repo-relative, forward slashes
    line: int           # 1-based; 0 = whole-file / cross-file finding
    message: str

    def format(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.check,
                                   self.message)

    def to_json(self) -> dict:
        return {"check": self.check, "path": self.path,
                "line": self.line, "message": self.message}


class SourceFile:
    """One parsed source file: text, line table, AST (None when the
    file does not parse — a syntax error is reported as a finding by
    run_checks, not an exception)."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
            self.error: Optional[str] = None
        except SyntaxError as e:
            self.tree = None
            self.error = "line %s: %s" % (e.lineno, e.msg)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allowed(self, lineno: int) -> set:
        """Check ids suppressed at `lineno` via allow() pragmas on the
        line itself or the line directly above."""
        out: set = set()
        for ln in (lineno, lineno - 1):
            m = PRAGMA_RE.search(self.line_at(ln))
            if m:
                out |= {c.strip() for c in m.group(1).split(",")
                        if c.strip()}
        return out

    def segment(self, node: ast.AST) -> str:
        """Best-effort source text of a node (for messages)."""
        try:
            return ast.get_source_segment(self.text, node) or ""
        except Exception:
            return ""


class Tree:
    """The scanned source tree: {repo-relative path: SourceFile}."""

    #: default scan roots, relative to the repo root
    ROOTS = ("presto_tpu", "tools")

    def __init__(self, root: str, files: Dict[str, SourceFile]):
        self.root = root
        self.files = files

    @classmethod
    def collect(cls, root: str,
                roots: Sequence[str] = ROOTS) -> "Tree":
        files: Dict[str, SourceFile] = {}
        for sub in roots:
            top = os.path.join(root, sub)
            for dirpath, dirs, names in os.walk(top):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for name in sorted(names):
                    if not name.endswith(".py"):
                        continue
                    p = os.path.join(dirpath, name)
                    rel = os.path.relpath(p, root).replace(os.sep, "/")
                    with open(p, encoding="utf-8") as f:
                        files[rel] = SourceFile(rel, f.read())
        return cls(root, files)

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     root: str = "<memory>") -> "Tree":
        return cls(root, {rel: SourceFile(rel, text)
                          for rel, text in sources.items()})

    def under(self, *prefixes: str) -> List[SourceFile]:
        """Files whose path starts with any prefix, sorted."""
        return [self.files[rel] for rel in sorted(self.files)
                if rel.startswith(prefixes)]

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)


# ---------------------------------------------------------------------------
# check registry
# ---------------------------------------------------------------------------

CheckFn = Callable[[Tree], List[Finding]]
_REGISTRY: Dict[str, CheckFn] = {}


def register(name: str):
    """Register a check family under `name` (its Finding.check id)."""
    def deco(fn: CheckFn) -> CheckFn:
        _REGISTRY[name] = fn
        return fn
    return deco


def registered_checks() -> List[str]:
    return sorted(_REGISTRY)


def run_checks(tree: Tree,
               checks: Optional[Sequence[str]] = None
               ) -> List[Finding]:
    """Run the selected (default: all registered) check families and
    return pragma-filtered findings, sorted by (path, line, check).
    Unparseable files yield one `syntax` finding each."""
    findings: List[Finding] = []
    for rel in sorted(tree.files):
        sf = tree.files[rel]
        if sf.error is not None:
            findings.append(Finding("syntax", rel, 0, sf.error))
    names = list(checks) if checks is not None else registered_checks()
    for name in names:
        try:
            fn = _REGISTRY[name]
        except KeyError:
            raise ValueError("unknown check %r (registered: %s)"
                             % (name, ", ".join(registered_checks())))
        findings.extend(fn(tree))
    kept = []
    for f in findings:
        sf = tree.get(f.path)
        if sf is not None and f.line and f.check in sf.allowed(f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.check, f.message))
    return kept


# ---------------------------------------------------------------------------
# baseline (grandfathered sites)
# ---------------------------------------------------------------------------

def baseline_entry(tree: Tree, finding: Finding,
                   note: str = "") -> dict:
    """The baseline row for one current finding: keyed by the stripped
    source line so later code motion neither orphans nor widens it."""
    sf = tree.get(finding.path)
    ctx = sf.line_at(finding.line).strip() if sf else ""
    return {"check": finding.check, "path": finding.path,
            "context": ctx, "note": note}


def load_baseline(path: str) -> List[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    entries = data.get("entries", []) if isinstance(data, dict) \
        else data
    return [e for e in entries if isinstance(e, dict)]


def save_baseline(path: str, entries: List[dict]) -> None:
    from presto_tpu.io.atomic import atomic_write_text
    atomic_write_text(path, json.dumps(
        {"version": 1,
         "comment": "grandfathered presto-lint sites; entries match "
                    "on (check, path, stripped source line) and a "
                    "stale entry fails the run — this file only "
                    "shrinks",
         "entries": entries}, indent=1, sort_keys=True) + "\n")


def _entry_matches(tree: Tree, entry: dict, finding: Finding) -> bool:
    if entry.get("check") != finding.check \
            or entry.get("path") != finding.path:
        return False
    ctx = entry.get("context", "")
    if not ctx:
        return True                       # path-wide grandfather
    sf = tree.get(finding.path)
    if sf is None:
        return False
    return sf.line_at(finding.line).strip() == ctx


def apply_baseline(tree: Tree, findings: List[Finding],
                   entries: List[dict]
                   ) -> Tuple[List[Finding], List[Finding],
                              List[Finding]]:
    """Split findings against the baseline.

    Returns (kept, suppressed, stale): `kept` are live violations,
    `suppressed` matched a baseline entry, and `stale` is one
    synthetic ``baseline`` finding per entry that matched nothing —
    stale entries fail the run so the baseline expires as sites are
    fixed."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    used = [False] * len(entries)
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if _entry_matches(tree, e, f):
                used[i] = True
                hit = True
        (suppressed if hit else kept).append(f)
    stale = [
        Finding("baseline", e.get("path", "?"), 0,
                "stale baseline entry (check=%s, context=%r) matches "
                "no current finding — remove it"
                % (e.get("check"), e.get("context", "")))
        for i, e in enumerate(entries) if not used[i]]
    return kept, suppressed, stale


# ---------------------------------------------------------------------------
# shared AST helpers for the check modules
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def const_strings(node: ast.AST) -> List[str]:
    """Every string constant anywhere under `node`."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant)
            and isinstance(n.value, str)]


@dataclass
class FunctionScope:
    """A function body with resolved innermost ownership of each
    statement — used by checks that reason per enclosing function."""
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    qualname: str
    calls: List[ast.Call] = field(default_factory=list)


def function_scopes(sf: SourceFile) -> List[FunctionScope]:
    """Every function/method in the file with its *directly owned*
    calls (calls inside nested defs belong to the nested scope)."""
    if sf.tree is None:
        return []
    out: List[FunctionScope] = []

    def walk_fn(node, qual):
        scope = FunctionScope(node, qual)
        out.append(scope)
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(n, qual + "." + n.name)
                continue
            if isinstance(n, ast.Call):
                scope.calls.append(n)
            stack.extend(ast.iter_child_nodes(n))

    def walk_top(node, prefix):
        for n in ast.iter_child_nodes(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(n, prefix + n.name)
            elif isinstance(n, ast.ClassDef):
                walk_top(n, prefix + n.name + ".")
            else:
                walk_top(n, prefix)

    walk_top(sf.tree, "")
    return out
