"""lock-guard / lock-order: statically-checked lock discipline.

The serve layer is the one place presto_tpu is genuinely concurrent —
replica pump threads, the scheduler, heartbeats, HTTP handlers — and
its shared state is guarded by per-object locks.  Chaos tests sample
races; this check eliminates a whole class of them statically.

**Declaration** is in-source, next to the lock:

    self._inflight_lock = threading.Lock()  # presto-lint: guards(_inflight)

declares that ``self._inflight`` may only be read or written inside a
``with self._inflight_lock:`` block in that class.  A
``threading.Condition(self._lock)`` assigned to an attribute aliases
its lock: holding the condition counts as holding the lock (that is
what entering a condition does).  Undeclared classes are not
enforced — the check is opt-in per lock, so annotating a class is a
reviewed statement of its concurrency contract.

Rules:

* ``__init__`` is exempt (attributes are born before threads exist);
* a function nested inside a method starts with *no* held locks (it
  typically runs on another thread — exactly the bug this catches);
* a method whose whole body runs under a caller's lock declares it:
  ``def _drain_locked(self):  # presto-lint: holds(_lock)``.

**lock-order** additionally records every syntactic nesting
``with self._a: ... with self._b:`` as a directed edge ``A -> B`` on
the class's lock graph (self-locks only — cross-object acquisition
through method calls is not visible statically) and fails on any
cycle across the scanned tree: two threads taking the same two locks
in opposite orders is a deadlock waiting for load.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from presto_tpu.lint.core import Finding, Tree, dotted_name, register

CHECK_GUARD = "lock-guard"
CHECK_ORDER = "lock-order"

GUARDS_RE = re.compile(r"#\s*presto-lint:\s*guards\(([^)]*)\)")
HOLDS_RE = re.compile(r"#\s*presto-lint:\s*holds\(([^)]*)\)")

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
COND_CTORS = {"threading.Condition", "Condition"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassLocks:
    """Lock declarations of one class: lock/condition attrs (mapped to
    their root lock) and the guarded-attribute table."""

    def __init__(self) -> None:
        self.roots: Dict[str, str] = {}     # lock/cond attr -> root
        self.guards: Dict[str, str] = {}    # guarded attr -> root

    def scan(self, cls: ast.ClassDef, sf) -> None:
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            ctor = dotted_name(node.value.func)
            targets = [a for a in map(_self_attr, node.targets) if a]
            if not targets or ctor is None:
                continue
            attr = targets[0]
            if ctor in LOCK_CTORS:
                self.roots[attr] = attr
                m = GUARDS_RE.search(sf.line_at(node.lineno))
                if m:
                    for g in m.group(1).split(","):
                        g = g.strip()
                        if g:
                            self.guards[g] = attr
            elif ctor in COND_CTORS:
                base = None
                if node.value.args:
                    base = _self_attr(node.value.args[0])
                self.roots[attr] = self.roots.get(base, base) \
                    if base else attr


def _holds_pragma(sf, fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for ln in (fn.lineno, fn.lineno - 1):
        m = HOLDS_RE.search(sf.line_at(ln))
        if m:
            out |= {h.strip() for h in m.group(1).split(",")
                    if h.strip()}
    return out


@register(CHECK_GUARD)
def check_guard(tree: Tree) -> List[Finding]:
    return _run(tree)[0]


@register(CHECK_ORDER)
def check_order(tree: Tree) -> List[Finding]:
    return _run(tree)[1]


def _run(tree: Tree) -> Tuple[List[Finding], List[Finding]]:
    guard_findings: List[Finding] = []
    edges: Dict[Tuple[str, str], int] = {}   # (fromkey, tokey) -> line
    edge_paths: Dict[Tuple[str, str], str] = {}

    for sf in tree.under("presto_tpu/", "tools/"):
        if sf.tree is None:
            continue
        for cls in [n for n in ast.walk(sf.tree)
                    if isinstance(n, ast.ClassDef)]:
            decl = _ClassLocks()
            decl.scan(cls, sf)
            if not decl.roots:
                continue
            key = "%s:%s" % (sf.path, cls.name)
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue
                held = frozenset(
                    decl.roots.get(h, h)
                    for h in _holds_pragma(sf, fn))
                _visit(fn, held, decl, sf, key, fn.name,
                       guard_findings, edges, edge_paths,
                       skip_self=True)

    order_findings = _cycles(edges, edge_paths)
    return guard_findings, order_findings


def _visit(node: ast.AST, held: FrozenSet[str], decl: _ClassLocks,
           sf, clskey: str, method: str,
           findings: List[Finding], edges, edge_paths,
           skip_self: bool = False) -> None:
    """Walk one statement/expression tracking the held-lock set."""
    if not skip_self:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested callable: usually another thread's body — it
            # holds nothing (its own holds() pragma may say otherwise)
            inner = frozenset(
                decl.roots.get(h, h) for h in _holds_pragma(sf, node)
            ) if not isinstance(node, ast.Lambda) else frozenset()
            for child in ast.iter_child_nodes(node):
                _visit(child, inner, decl, sf, clskey,
                       method, findings, edges, edge_paths)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: List[str] = []
            for item in node.items:
                attr = _self_attr(item.context_expr)
                root = decl.roots.get(attr) if attr else None
                if root is not None:
                    for h in held:
                        if h != root:
                            e = (clskey + "." + h, clskey + "." + root)
                            edges.setdefault(e, node.lineno)
                            edge_paths.setdefault(e, sf.path)
                    newly.append(root)
                elif item.context_expr is not None:
                    _visit(item.context_expr, held, decl, sf, clskey,
                           method, findings, edges, edge_paths)
            inner = held.union(newly)
            for stmt in node.body:
                _visit(stmt, inner, decl, sf, clskey, method,
                       findings, edges, edge_paths)
            return
        attr = _self_attr(node)
        if attr is not None and attr in decl.guards \
                and decl.guards[attr] not in held:
            findings.append(Finding(
                CHECK_GUARD, sf.path, node.lineno,
                "self.%s is guarded by self.%s but %s() touches it "
                "without holding the lock (declare the guard with "
                "`with self.%s:` or mark the method "
                "`# presto-lint: holds(%s)` if every caller holds "
                "it)" % (attr, decl.guards[attr], method,
                         decl.guards[attr], decl.guards[attr])))
            return
    for child in ast.iter_child_nodes(node):
        _visit(child, held, decl, sf, clskey, method, findings,
               edges, edge_paths)


def _cycles(edges, edge_paths) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: List[Finding] = []
    seen_cycles: Set[FrozenSet[str]] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}

    def dfs(n: str, stack: List[str]) -> None:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GRAY:
                cyc = stack[stack.index(m):] + [m]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    e = (cyc[0], cyc[1]) if len(cyc) > 1 \
                        else (cyc[0], cyc[0])
                    out.append(Finding(
                        CHECK_ORDER, edge_paths.get(
                            (n, m), e and edge_paths.get(e, "?")),
                        edges.get((n, m), 0),
                        "lock-acquisition-order cycle: %s — two "
                        "threads taking these locks in opposite "
                        "orders deadlock" % " -> ".join(cyc)))
            elif color.get(m, WHITE) == WHITE:
                dfs(m, stack)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            dfs(n, [])
    return out
