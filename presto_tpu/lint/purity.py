"""trace-purity: jit-reachable code never calls time/random/host-I/O.

PAPER.md's determinism contract — re-running a stage produces
byte-identical artifacts, which is what lets the manifest verify
instead of trust and lets chaos tests assert equality after a kill —
holds only if everything that executes *at trace time* inside
``jax.jit`` / ``pjit`` / Pallas entry points is a pure function of its
inputs.  A ``time.time()`` or ``np.random`` call in traced code bakes
a different constant into every compile; host file I/O from inside a
traced function runs at trace time (once, unpredictably, per compile)
rather than per call.  Chaos and equality tests only sample this;
the check proves it over the whole call graph.

Mechanics: over ``ops/``, ``search/``, ``parallel/`` the check

1. marks **entry points**: functions decorated ``@jax.jit`` /
   ``@partial(jax.jit, ...)`` / ``@pjit``, functions wrapped by a
   ``jax.jit(f)`` / ``jax.jit(jax.vmap(f))`` call, and kernels handed
   to ``pl.pallas_call``;
2. builds the **call graph** by name: bare calls resolve to functions
   of the same module (including nested defs), ``from``-imports and
   ``module.func`` attribute calls resolve across the three scanned
   packages;
3. flags any **impure call** in a reachable function: ``time.time``
   and friends, the stateful ``random`` / ``numpy.random`` modules
   (``jax.random`` is fine — functional PRNG keys are the supported
   way), builtin ``open`` / ``os`` file mutations, and ``.tofile``.

Per-site escapes use the standard pragma, e.g. a host callback that
is deliberately impure:  ``# presto-lint: allow(trace-purity)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from presto_tpu.lint.core import (Finding, SourceFile, Tree,
                                  dotted_name, function_scopes,
                                  register)

CHECK = "trace-purity"

SCOPES = ("presto_tpu/ops/", "presto_tpu/search/",
          "presto_tpu/parallel/")

JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pjit",
                "jax.experimental.pjit.pjit"}
PARTIALS = {"partial", "functools.partial"}
UNWRAP = {"jax.vmap", "vmap", "jax.named_call", "shard_map",
          "jax.checkpoint", "checkpoint"} | PARTIALS | JIT_WRAPPERS

IMPURE_EXACT = {
    "open", "input", "os.fdopen", "os.remove", "os.unlink",
    "os.replace", "os.rename", "os.makedirs", "os.mkdir",
    "os.system", "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "time.monotonic_ns",
    "time.sleep",
}
IMPURE_PREFIX = ("random.", "numpy.random.")


class _Module:
    """One scanned module: alias maps, function table, jit roots."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.aliases: Dict[str, str] = {}      # import numpy as np
        self.from_imports: Dict[str, str] = {}  # from x import y
        self.funcs: Dict[str, List] = {}       # bare name -> scopes
        self.scopes = function_scopes(sf)
        for scope in self.scopes:
            bare = scope.qualname.rsplit(".", 1)[-1]
            self.funcs.setdefault(bare, []).append(scope)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.aliases[local] = a.name if a.asname \
                        else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        node.module + "." + a.name

    def resolve_dotted(self, d: str) -> str:
        head, _, rest = d.partition(".")
        if head in self.from_imports:
            base = self.from_imports[head]
        elif head in self.aliases:
            base = self.aliases[head]
        else:
            return d
        return base + "." + rest if rest else base


def _module_rel(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


def _collect_jit_roots(mod: _Module) -> Set[str]:
    """Qualnames of jit/pallas entry points in one module."""
    roots: Set[str] = set()
    by_node = {id(s.node): s for s in mod.scopes}

    def mark_name(name: Optional[str]) -> None:
        if name:
            for scope in mod.funcs.get(name, ()):
                roots.add(scope.qualname)

    def names_under(node: ast.AST) -> List[str]:
        """Bare function names inside a wrapper expression like
        jax.jit(jax.vmap(f)) or partial(f, ...)."""
        out: List[str] = []
        if isinstance(node, ast.Name):
            out.append(node.id)
        elif isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn in UNWRAP or fn is None:
                for a in node.args:
                    out.extend(names_under(a))
        return out

    # decorator-based roots
    for scope in mod.scopes:
        node = scope.node
        for dec in getattr(node, "decorator_list", ()):
            d = dotted_name(dec)
            if d in JIT_WRAPPERS:
                roots.add(scope.qualname)
                continue
            if isinstance(dec, ast.Call):
                fn = dotted_name(dec.func)
                if fn in JIT_WRAPPERS:
                    roots.add(scope.qualname)
                elif fn in PARTIALS and dec.args \
                        and dotted_name(dec.args[0]) in JIT_WRAPPERS:
                    roots.add(scope.qualname)
    # call-based roots: jax.jit(f) anywhere, pallas_call(kernel, ...)
    for node in ast.walk(mod.sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn in JIT_WRAPPERS:
            for a in node.args:
                for name in names_under(a):
                    mark_name(name)
        elif fn is not None and fn.endswith("pallas_call") \
                and node.args:
            for name in names_under(node.args[0]):
                mark_name(name)
    del by_node
    return roots


@register(CHECK)
def check(tree: Tree) -> List[Finding]:
    mods: Dict[str, _Module] = {}
    for sf in tree.under(*SCOPES):
        if sf.tree is not None:
            mods[sf.path] = _Module(sf)

    # call-graph edges: (path, qualname) -> [(path, qualname)]
    def edges(path: str, scope) -> List[Tuple[str, str]]:
        mod = mods[path]
        out: List[Tuple[str, str]] = []
        for call in scope.calls:
            d = dotted_name(call.func)
            if d is None:
                continue
            if "." not in d:
                # bare call: same-module function (any nesting), or a
                # from-import from a scanned module
                if d in mod.funcs:
                    out.extend((path, s.qualname)
                               for s in mod.funcs[d])
                    continue
                tgt = mod.from_imports.get(d)
                if tgt:
                    tmod, _, tname = tgt.rpartition(".")
                    rel = _module_rel(tmod)
                    if rel in mods and tname in mods[rel].funcs:
                        out.extend((rel, s.qualname)
                                   for s in mods[rel].funcs[tname])
            else:
                head, _, attr = d.partition(".")
                if "." in attr:
                    continue               # a.b.c: not a module func
                base = mod.from_imports.get(head) \
                    or mod.aliases.get(head)
                if base:
                    rel = _module_rel(base)
                    if rel in mods and attr in mods[rel].funcs:
                        out.extend((rel, s.qualname)
                                   for s in mods[rel].funcs[attr])
        return out

    scope_by_key = {(path, s.qualname): s
                    for path, mod in mods.items()
                    for s in mod.scopes}

    # BFS from every jit root, remembering which root reached where
    reached: Dict[Tuple[str, str], str] = {}
    queue: List[Tuple[Tuple[str, str], str]] = []
    for path, mod in mods.items():
        for qual in sorted(_collect_jit_roots(mod)):
            key = (path, qual)
            if key in scope_by_key and key not in reached:
                reached[key] = "%s:%s" % (path, qual)
                queue.append((key, reached[key]))
    while queue:
        key, root = queue.pop()
        for nxt in edges(key[0], scope_by_key[key]):
            if nxt not in reached and nxt in scope_by_key:
                reached[nxt] = root
                queue.append((nxt, root))

    out: List[Finding] = []
    for (path, qual), root in sorted(reached.items()):
        mod = mods[path]
        for call in scope_by_key[(path, qual)].calls:
            d = dotted_name(call.func)
            if d is None:
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr == "tofile":
                    out.append(Finding(
                        CHECK, path, call.lineno,
                        "%s (reachable from jit entry %s) calls "
                        ".tofile() — host I/O inside traced code "
                        "breaks the byte-identity contract"
                        % (qual, root)))
                continue
            r = mod.resolve_dotted(d)
            if r in IMPURE_EXACT \
                    or r.startswith(IMPURE_PREFIX):
                out.append(Finding(
                    CHECK, path, call.lineno,
                    "%s (reachable from jit entry %s) calls %s — "
                    "trace-impure: the value is baked in at trace "
                    "time, so recompiles stop being byte-identical "
                    "(use jax.random keys / pass host state as an "
                    "argument)" % (qual, root, r)))
    return out
