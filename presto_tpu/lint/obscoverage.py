"""obs-coverage: the instrumentation-coverage contract (20 checks).

Formerly ``tools/obs_lint.py`` (a thin shim remains there for the
historical entry point); now the fifth presto-lint family.  The
observability contract lives in presto_tpu/obs/taxonomy.py; these
checks cross-check the *source tree* against it so an uninstrumented
code path cannot ship silently:

  1. every `timer.mark("<stage>")` in pipeline/survey.py is a
     registered SURVEY_STAGE (=> it emits a
     survey_stage_seconds{stage=...} sample and a span);
  2. every `_chaos(cfg, "<point>", ...)` kill point is a registered
     KILL_POINT (=> it is flight-recorded before it can fire) — and
     conversely every registered point still exists in the source;
  2b. every elastic-cluster kill point (`self._point("...")` in
     parallel/elastic.py) and event (`.event("...")`/`._event("...")`
     in parallel/elastic.py + pipeline/shardledger.py) is registered
     in CLUSTER_KILL_POINTS / CLUSTER_EVENTS — and conversely;
  3. every `events.emit("<kind>", ...)` in presto_tpu/serve/ is a
     registered SERVE_EVENT;
  4. every job lifecycle state (JobStatus constants in serve/queue.py)
     maps via JOB_STATE_EVENTS to an event kind that the serve layer
     actually emits — a new scheduler state transition without
     telemetry fails here;
  5. every metric registered anywhere in presto_tpu/ or tools/
     (`.counter("..." / .gauge("..." / .histogram("...`) is listed in
     METRICS (the documented catalog);
  6. the tune layer (presto_tpu/tune/ + apps/tune.py): every
     `obs.span("...")` name it opens is registered in TUNE_SPANS —
     and conversely; and every `tune_*` metric listed in METRICS is
     actually registered by the tune layer (the forward direction is
     check 5), so a tuning code path cannot ship unobservable and the
     catalog cannot list dead tuning telemetry;
  7. the streaming layer (presto_tpu/stream/): spans vs STREAM_SPANS
     and event kinds vs STREAM_EVENTS, BOTH directions, plus every
     `stream_*` metric listed in METRICS registered by the stream
     layer — the live trigger path is the one place an unobservable
     code path costs real pulses, so its whole telemetry vocabulary
     is pinned;
  8. the fused pipeline (presto_tpu/pipeline/fusion.py): every
     `obs.span("pipeline:...")` it opens is registered in
     FUSION_SPANS — and conversely — and every `survey_fused_*`
     metric listed in METRICS is actually registered by the fusion
     layer, so the in-memory data path (which deliberately SKIPS the
     durable artifacts a post-mortem would otherwise read) cannot
     ship with its telemetry dark;
  9. the DM-SHARDED seam (the multi-device arm of the fused
     pipeline): SHARDED_FUSION_SPANS / SHARDED_KILL_POINTS /
     SHARDED_FUSION_METRICS are pinned BOTH directions against the
     source (and as subsets of their parent catalogs);
  10. the FLEET serving layer (serve/jobledger.py + serve/fleet.py +
     serve/router.py): FLEET_EVENTS and the `fleet_*` metrics are
     pinned BOTH directions (event kinds count whether emitted
     literally or bound as LeaseLedger EV_* class attributes);
  11. serve-layer spans (presto_tpu/serve/): every `obs.span("...")`
     name the serve layer opens is registered in SERVE_SPANS — and
     conversely;
  12. discovery DAGs (serve/dag.py + jobledger.py + router.py +
     fleet.py): DAG_EVENTS / DAG_SPANS / DAG_METRICS pinned BOTH
     directions (and as subsets of their parent catalogs);
  13. fleet-wide observability (serve/fleet.py + serve/router.py +
     obs/fleetagg.py): FLEET_SPANS / FLEET_OBS_EVENTS /
     FLEET_OBS_METRICS pinned BOTH directions and as subsets of
     their parent catalogs;
  14. the SLO observatory (obs/slo.py + serve/jobledger.py +
     serve/router.py): SLO_METRICS / SLO_EVENTS / SLO_SPANS pinned
     BOTH directions (and as subsets of their parent catalogs) — the
     usage metering at the fence-checked commit and the burn/scale
     decision signals are the contract future control-plane PRs
     (autoscaler, device-seconds admission) inherit, so they may
     neither go dark nor go stale;
  15. the kernel observatory (obs/costmodel.py + obs/roofline.py +
     bench.py): COST_SPANS (`obs:roofline-probe`) / COST_METRICS
     (kernel_flops_total, kernel_hbm_bytes_total,
     cost_model_unavailable) pinned BOTH directions (and as a subset
     of METRICS) — the per-kind FLOP/byte dispatch join is the
     measurement rig every remaining perf item (Pallas dedisp, GPU
     backend, learned tuner) is judged by;
  16. the fleet supervisor (serve/supervisor.py + serve/router.py +
     serve/jobledger.py): SUPERVISOR_EVENTS / SUPERVISOR_SPANS /
     SUPERVISOR_METRICS pinned BOTH directions (and as subsets of
     their parent catalogs) — the control loop that actuates /scale
     must leave a reconstructable trail (every spawn/drain/hold with
     its inputs), so its telemetry vocabulary is pinned the moment it
     ships;
  17. the campaign engine (serve/campaign.py + serve/router.py +
     serve/supervisor.py): CAMPAIGN_EVENTS / CAMPAIGN_SPANS /
     CAMPAIGN_METRICS pinned BOTH directions (and as subsets of their
     parent catalogs) — archive-scale reprocessing is driven entirely
     from a durable ledger, so every admission wave, yield decision,
     and paced preemption must land on telemetry a post-mortem can
     replay; a campaign code path without its vocabulary (or a stale
     vocabulary entry) fails here;
  18. the beam multiplexer (stream/beams.py): BEAM_EVENTS /
     BEAM_SPANS / BEAM_METRICS pinned BOTH directions (and as
     subsets of their parent catalogs), plus the three-way
     kill-point pin (taxonomy == beams.BEAM_KILL_POINTS ==
     testing/chaos re-export);
  19. the federation front door (serve/federation.py): FED_EVENTS /
     FED_SPANS / FED_METRICS pinned BOTH directions (and as subsets
     of their parent catalogs), plus the three-way kill-point pin
     (taxonomy == federation.FED_KILL_POINTS == testing/chaos
     re-export) — whole-fleet failover runs exactly while a site is
     dying, so every placement, spill, re-admission, and fenced
     zombie commit must land on telemetry a post-mortem can replay;
  20. learned candidate triage (presto_tpu/triage/ + the serve/dag.py
     triage node + apps/triage.py): TRIAGE_EVENTS / TRIAGE_SPANS /
     TRIAGE_METRICS pinned BOTH directions (and as subsets of their
     parent catalogs) — triage decides which candidates are never
     folded, so every learned selection, heuristic degrade
     (missing/corrupt weights), and calibration run must land on
     telemetry a post-mortem can replay.

Run via tools/presto_lint.py (exit-1 CLI over every family), the
legacy tools/obs_lint.py shim, or tests/test_obs_lint.py.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Set

from presto_tpu.lint.core import Finding, Tree, register

#: the repo root this package is installed in (three levels up)
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STAGE_RE = re.compile(r'timer\.mark\(\s*"([^"]+)"\s*\)')
CHAOS_RE = re.compile(r'_chaos\(\s*cfg\s*,\s*"([^"]+)"')
EMIT_RE = re.compile(r'events\.emit\(\s*"([^"]+)"')
POINT_RE = re.compile(r'\._point\(\s*\n?\s*"([^"]+)"')
CLUSTER_EVENT_RE = re.compile(r'\._?event\(\s*\n?\s*"([^"]+)"')
STATUS_RE = re.compile(r'^\s+([A-Z_]+)\s*=\s*"([a-z-]+)"\s*$',
                       re.MULTILINE)
#: event kinds bound as ledger class attributes (the generic
#: LeaseLedger emits via EV_* names; subclasses declare the literal
#: vocabulary — see pipeline/leaseledger.py)
EVENT_ATTR_RE = re.compile(r'^\s*EV_[A-Z_]+\s*=\s*"([^"]+)"',
                           re.MULTILINE)
METRIC_RE = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*\n?\s*"([a-z0-9_]+)"')
SPAN_RE = re.compile(r'\.span\(\s*\n?\s*"([^"]+)"')


def _read(relpath: str, root: str) -> str:
    with open(os.path.join(root, relpath)) as f:
        return f.read()


def _tree_sources(root: str, *roots: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for sub in roots:
        for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
            for name in files:
                if name.endswith(".py"):
                    p = os.path.join(dirpath, name)
                    rel = os.path.relpath(p, root)
                    with open(p) as f:
                        out[rel] = f.read()
    return out


def lint(root: Optional[str] = None) -> List[str]:
    """Run every coverage check; returns a list of violation strings
    (the historical obs_lint API, kept for the shim and tests)."""
    root = root or REPO
    if root not in sys.path:
        sys.path.insert(0, root)
    from presto_tpu.obs import taxonomy

    problems: List[str] = []
    survey_src = _read("presto_tpu/pipeline/survey.py", root)

    # 1. survey stages
    stages = set(STAGE_RE.findall(survey_src))
    for s in sorted(stages - taxonomy.SURVEY_STAGES):
        problems.append(
            "pipeline/survey.py: stage %r is not registered in "
            "obs/taxonomy.SURVEY_STAGES (uninstrumented stage)" % s)
    for s in sorted(taxonomy.SURVEY_STAGES - stages):
        problems.append(
            "obs/taxonomy.py: SURVEY_STAGES lists %r but "
            "pipeline/survey.py never marks it" % s)

    # 2. chaos kill points (both directions: the taxonomy IS the
    # documented flight-recorder vocabulary)
    points = set(CHAOS_RE.findall(survey_src))
    for p in sorted(points - taxonomy.KILL_POINTS):
        problems.append(
            "pipeline/survey.py: kill point %r is not registered in "
            "obs/taxonomy.KILL_POINTS" % p)
    for p in sorted(taxonomy.KILL_POINTS - points):
        problems.append(
            "obs/taxonomy.py: KILL_POINTS lists %r but "
            "pipeline/survey.py never fires it" % p)

    # 2b. elastic-cluster kill points and events (parallel/elastic.py
    # + pipeline/shardledger.py are the worker-loss recovery layer;
    # its kill points and flight-recorder events are a registered
    # vocabulary exactly like the survey's — since the ledger core
    # moved to pipeline/leaseledger.py, shardledger declares its
    # event kinds as EV_* class attributes, which count as emitted)
    elastic_files = ("presto_tpu/parallel/elastic.py",
                     "presto_tpu/pipeline/shardledger.py")
    cpoints: Set[str] = set()
    cevents: Set[str] = set()
    for rel in elastic_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        cpoints |= set(POINT_RE.findall(src))
        cevents |= set(CLUSTER_EVENT_RE.findall(src))
        cevents |= set(EVENT_ATTR_RE.findall(src))
    for p in sorted(cpoints - taxonomy.CLUSTER_KILL_POINTS):
        problems.append(
            "parallel/elastic.py: kill point %r is not registered in "
            "obs/taxonomy.CLUSTER_KILL_POINTS" % p)
    for p in sorted(taxonomy.CLUSTER_KILL_POINTS - cpoints):
        problems.append(
            "obs/taxonomy.py: CLUSTER_KILL_POINTS lists %r but the "
            "elastic layer never fires it" % p)
    for k in sorted(cevents - taxonomy.CLUSTER_EVENTS):
        problems.append(
            "elastic layer: event kind %r is not registered in "
            "obs/taxonomy.CLUSTER_EVENTS" % k)
    for k in sorted(taxonomy.CLUSTER_EVENTS - cevents):
        problems.append(
            "obs/taxonomy.py: CLUSTER_EVENTS lists %r but the "
            "elastic layer never emits it" % k)

    # 3. serve event kinds (the fleet and DAG modules share the serve
    # event log, so their registered vocabularies — FLEET_EVENTS /
    # DAG_EVENTS, pinned both directions by checks 10/12 — are
    # admissible here too)
    serve_srcs = _tree_sources(root, "presto_tpu/serve")
    serve_ok = (taxonomy.SERVE_EVENTS | taxonomy.FLEET_EVENTS
                | taxonomy.DAG_EVENTS | taxonomy.SLO_EVENTS
                | taxonomy.SUPERVISOR_EVENTS
                | taxonomy.CAMPAIGN_EVENTS | taxonomy.FED_EVENTS
                | taxonomy.TRIAGE_EVENTS)
    emitted: Set[str] = set()
    for rel, src in sorted(serve_srcs.items()):
        kinds = set(EMIT_RE.findall(src))
        emitted |= kinds
        for k in sorted(kinds - serve_ok):
            problems.append(
                "%s: event kind %r is not registered in "
                "obs/taxonomy.SERVE_EVENTS, FLEET_EVENTS, "
                "DAG_EVENTS, SLO_EVENTS, SUPERVISOR_EVENTS, "
                "CAMPAIGN_EVENTS, FED_EVENTS, or TRIAGE_EVENTS"
                % (rel, k))

    # 4. every job lifecycle state announces itself (scoped to the
    # JobStatus class body: queue.py also defines the Lanes constants,
    # which are scheduling classes, not lifecycle states)
    queue_src = serve_srcs.get("presto_tpu/serve/queue.py", "")
    m = re.search(r'class JobStatus.*?(?=\nclass |\Z)', queue_src,
                  re.DOTALL)
    states = {v for _name, v in STATUS_RE.findall(m.group(0) if m
                                                  else queue_src)}
    for state in sorted(states):
        kind = taxonomy.JOB_STATE_EVENTS.get(state)
        if kind is None:
            problems.append(
                "serve/queue.py: JobStatus %r has no event mapping "
                "in obs/taxonomy.JOB_STATE_EVENTS (silent scheduler "
                "state transition)" % state)
        elif kind not in emitted:
            problems.append(
                "serve layer: state %r maps to event %r which no "
                "serve module emits" % (state, kind))

    # 5. metric names vs the documented catalog
    for rel, src in sorted(_tree_sources(root, "presto_tpu",
                                         "tools").items()):
        for name in sorted(set(METRIC_RE.findall(src))):
            if name not in taxonomy.METRICS:
                problems.append(
                    "%s: metric %r is not listed in "
                    "obs/taxonomy.METRICS (undocumented metric)"
                    % (rel, name))

    # 6. tune layer: spans both ways + tune_* metric reverse direction
    tune_srcs = _tree_sources(root, "presto_tpu/tune")
    try:
        tune_srcs["presto_tpu/apps/tune.py"] = \
            _read("presto_tpu/apps/tune.py", root)
    except OSError:
        pass
    tspans: Set[str] = set()
    tmetrics: Set[str] = set()
    for rel, src in sorted(tune_srcs.items()):
        spans = set(SPAN_RE.findall(src))
        tspans |= spans
        tmetrics |= set(METRIC_RE.findall(src))
        for s in sorted(spans - taxonomy.TUNE_SPANS):
            problems.append(
                "%s: span %r is not registered in "
                "obs/taxonomy.TUNE_SPANS (uninstrumented tuning "
                "path)" % (rel, s))
    for s in sorted(taxonomy.TUNE_SPANS - tspans):
        problems.append(
            "obs/taxonomy.py: TUNE_SPANS lists %r but the tune layer "
            "never opens it" % s)
    cataloged_tune = {m for m in taxonomy.METRICS
                      if m.startswith("tune_")}
    for name in sorted(cataloged_tune - tmetrics):
        problems.append(
            "obs/taxonomy.py: METRICS lists %r but the tune layer "
            "never registers it" % name)

    # 7. streaming layer: spans + events both ways, stream_* metric
    # reverse direction (forward is check 5)
    stream_srcs = _tree_sources(root, "presto_tpu/stream")
    sspans: Set[str] = set()
    sevents: Set[str] = set()
    smetrics: Set[str] = set()
    for rel, src in sorted(stream_srcs.items()):
        spans = set(SPAN_RE.findall(src))
        sspans |= spans
        sevents |= set(EMIT_RE.findall(src))
        smetrics |= set(METRIC_RE.findall(src))
        for s in sorted(spans - taxonomy.STREAM_SPANS):
            problems.append(
                "%s: span %r is not registered in "
                "obs/taxonomy.STREAM_SPANS (uninstrumented streaming "
                "path)" % (rel, s))
    for s in sorted(taxonomy.STREAM_SPANS - sspans):
        problems.append(
            "obs/taxonomy.py: STREAM_SPANS lists %r but the stream "
            "layer never opens it" % s)
    for k in sorted(sevents - taxonomy.STREAM_EVENTS):
        problems.append(
            "stream layer: event kind %r is not registered in "
            "obs/taxonomy.STREAM_EVENTS" % k)
    for k in sorted(taxonomy.STREAM_EVENTS - sevents):
        problems.append(
            "obs/taxonomy.py: STREAM_EVENTS lists %r but the stream "
            "layer never emits it" % k)
    cataloged_stream = {m for m in taxonomy.METRICS
                        if m.startswith("stream_")}
    for name in sorted(cataloged_stream - smetrics):
        problems.append(
            "obs/taxonomy.py: METRICS lists %r but the stream layer "
            "never registers it" % name)

    # 8. fused pipeline: seam spans both ways, survey_fused_* metric
    # reverse direction (forward is check 5)
    try:
        fusion_src = _read("presto_tpu/pipeline/fusion.py", root)
    except OSError:
        fusion_src = ""
    fspans = {s for s in SPAN_RE.findall(fusion_src)
              if s.startswith("pipeline:")}
    fmetrics = set(METRIC_RE.findall(fusion_src))
    for s in sorted(fspans - taxonomy.FUSION_SPANS):
        problems.append(
            "pipeline/fusion.py: span %r is not registered in "
            "obs/taxonomy.FUSION_SPANS (uninstrumented fused path)"
            % s)
    for s in sorted(taxonomy.FUSION_SPANS - fspans):
        problems.append(
            "obs/taxonomy.py: FUSION_SPANS lists %r but the fusion "
            "layer never opens it" % s)
    cataloged_fused = {m for m in taxonomy.METRICS
                       if m.startswith("survey_fused_")}
    for name in sorted(cataloged_fused - fmetrics):
        problems.append(
            "obs/taxonomy.py: METRICS lists %r but the fusion layer "
            "never registers it" % name)

    # 9. DM-sharded seam: spans/kill points/metrics both directions
    # (the sharded sets must also be subsets of their parent catalogs,
    # so a rename cannot leave a dangling sharded entry)
    for s in sorted(taxonomy.SHARDED_FUSION_SPANS
                    - taxonomy.FUSION_SPANS):
        problems.append(
            "obs/taxonomy.py: SHARDED_FUSION_SPANS lists %r which is "
            "not in FUSION_SPANS" % s)
    for p in sorted(taxonomy.SHARDED_KILL_POINTS
                    - taxonomy.KILL_POINTS):
        problems.append(
            "obs/taxonomy.py: SHARDED_KILL_POINTS lists %r which is "
            "not in KILL_POINTS" % p)
    for name in sorted(taxonomy.SHARDED_FUSION_METRICS
                       - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: SHARDED_FUSION_METRICS lists %r which "
            "is not in METRICS" % name)
    for s in sorted(taxonomy.SHARDED_FUSION_SPANS - fspans):
        problems.append(
            "obs/taxonomy.py: SHARDED_FUSION_SPANS lists %r but the "
            "fusion layer never opens it" % s)
    for s in sorted({x for x in fspans if "shard" in x}
                    - taxonomy.SHARDED_FUSION_SPANS):
        problems.append(
            "pipeline/fusion.py: sharded span %r is not registered "
            "in obs/taxonomy.SHARDED_FUSION_SPANS" % s)
    for p in sorted(taxonomy.SHARDED_KILL_POINTS - points):
        problems.append(
            "obs/taxonomy.py: SHARDED_KILL_POINTS lists %r but "
            "pipeline/survey.py never fires it" % p)
    for p in sorted({x for x in points if "shard" in x}
                    - taxonomy.SHARDED_KILL_POINTS):
        problems.append(
            "pipeline/survey.py: sharded kill point %r is not "
            "registered in obs/taxonomy.SHARDED_KILL_POINTS" % p)
    for name in sorted(taxonomy.SHARDED_FUSION_METRICS - fmetrics):
        problems.append(
            "obs/taxonomy.py: SHARDED_FUSION_METRICS lists %r but "
            "the fusion layer never registers it" % name)
    for name in sorted({x for x in fmetrics
                        if x.startswith("survey_fused_shard_")}
                       - taxonomy.SHARDED_FUSION_METRICS):
        problems.append(
            "pipeline/fusion.py: sharded metric %r is not registered "
            "in obs/taxonomy.SHARDED_FUSION_METRICS" % name)

    # 10. fleet serving (serve/jobledger.py + fleet.py + router.py):
    # FLEET_EVENTS and the fleet_* metrics are pinned BOTH directions
    # — the fleet recovery path (lease, fence, reap, shed, quota) is
    # exactly the code that runs while a replica is dying, so its
    # telemetry may neither go dark nor go stale.  Event kinds count
    # whether emitted literally (events.emit / obs.event) or bound as
    # LeaseLedger EV_* class attributes.
    fleet_files = ("presto_tpu/serve/jobledger.py",
                   "presto_tpu/serve/fleet.py",
                   "presto_tpu/serve/router.py")
    fl_events: Set[str] = set()
    fl_metrics: Set[str] = set()
    for rel in fleet_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        fl_events |= set(EMIT_RE.findall(src))
        fl_events |= set(CLUSTER_EVENT_RE.findall(src))
        fl_events |= set(EVENT_ATTR_RE.findall(src))
        fl_metrics |= set(METRIC_RE.findall(src))
    for k in sorted(taxonomy.FLEET_EVENTS - fl_events):
        problems.append(
            "obs/taxonomy.py: FLEET_EVENTS lists %r but the fleet "
            "layer never emits it" % k)
    for k in sorted(fl_events - taxonomy.FLEET_EVENTS
                    - taxonomy.SERVE_EVENTS - taxonomy.DAG_EVENTS
                    - taxonomy.SLO_EVENTS):
        problems.append(
            "fleet layer: event kind %r is not registered in "
            "obs/taxonomy.FLEET_EVENTS" % k)
    for name in sorted(taxonomy.FLEET_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: FLEET_METRICS lists %r which is not "
            "in METRICS" % name)
    for name in sorted(taxonomy.FLEET_METRICS - fl_metrics):
        problems.append(
            "obs/taxonomy.py: FLEET_METRICS lists %r but the fleet "
            "layer never registers it" % name)
    for name in sorted({x for x in fl_metrics
                        if x.startswith("fleet_")}
                       - taxonomy.FLEET_METRICS):
        problems.append(
            "fleet layer: metric %r is not registered in "
            "obs/taxonomy.FLEET_METRICS" % name)

    # 11. serve-layer spans both directions (the stacked batch
    # executor's cross-job span is the one covering the serving
    # tier's biggest device calls — it may neither go dark nor stay
    # in the catalog after a rename)
    svspans: Set[str] = set()
    for rel, src in sorted(serve_srcs.items()):
        spans = set(SPAN_RE.findall(src))
        svspans |= spans
        for s in sorted(spans - taxonomy.SERVE_SPANS):
            problems.append(
                "%s: span %r is not registered in "
                "obs/taxonomy.SERVE_SPANS (uninstrumented serve "
                "path)" % (rel, s))
    for s in sorted(taxonomy.SERVE_SPANS - svspans):
        problems.append(
            "obs/taxonomy.py: SERVE_SPANS lists %r but the serve "
            "layer never opens it" % s)

    # 12. discovery DAGs (serve/dag.py + jobledger.py + router.py +
    # fleet.py): DAG_EVENTS / DAG_SPANS / DAG_METRICS pinned BOTH
    # directions — the dependency-aware job graph is exactly the code
    # that runs while a mid-graph replica is dying (fenced fan-out,
    # cascade failure), so its telemetry may neither go dark nor go
    # stale; the dag sets must also be subsets of their parent
    # catalogs so a rename cannot leave a dangling entry.
    dag_files = ("presto_tpu/serve/dag.py",
                 "presto_tpu/serve/jobledger.py",
                 "presto_tpu/serve/router.py",
                 "presto_tpu/serve/fleet.py")
    dg_events: Set[str] = set()
    dg_spans: Set[str] = set()
    dg_metrics: Set[str] = set()
    for rel in dag_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        dg_events |= set(EMIT_RE.findall(src))
        dg_events |= set(CLUSTER_EVENT_RE.findall(src))
        dg_spans |= set(SPAN_RE.findall(src))
        dg_metrics |= set(METRIC_RE.findall(src))
    for s in sorted(taxonomy.DAG_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: DAG_SPANS lists %r which is not in "
            "SERVE_SPANS" % s)
    for name in sorted(taxonomy.DAG_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: DAG_METRICS lists %r which is not in "
            "METRICS" % name)
    for k in sorted(taxonomy.DAG_EVENTS - dg_events):
        problems.append(
            "obs/taxonomy.py: DAG_EVENTS lists %r but the dag layer "
            "never emits it" % k)
    for k in sorted({x for x in dg_events if x.startswith("dag-")}
                    - taxonomy.DAG_EVENTS):
        problems.append(
            "dag layer: event kind %r is not registered in "
            "obs/taxonomy.DAG_EVENTS" % k)
    for s in sorted(taxonomy.DAG_SPANS - dg_spans):
        problems.append(
            "obs/taxonomy.py: DAG_SPANS lists %r but the dag layer "
            "never opens it" % s)
    for s in sorted({x for x in dg_spans
                     if x.startswith("serve:dag")}
                    - taxonomy.DAG_SPANS):
        problems.append(
            "dag layer: span %r is not registered in "
            "obs/taxonomy.DAG_SPANS" % s)
    for name in sorted(taxonomy.DAG_METRICS - dg_metrics):
        problems.append(
            "obs/taxonomy.py: DAG_METRICS lists %r but the dag "
            "layer never registers it" % name)
    for name in sorted({x for x in dg_metrics
                        if x.startswith("dag_")}
                       - taxonomy.DAG_METRICS):
        problems.append(
            "dag layer: metric %r is not registered in "
            "obs/taxonomy.DAG_METRICS" % name)

    # 13. fleet-wide observability (serve/fleet.py + serve/router.py
    # + obs/fleetagg.py): the `fleet:` span prefix, the snapshot/
    # chaos event kinds, and the fleet_obs_*/job_e2e_seconds metrics
    # pinned BOTH directions + subset-of-parent — cross-process trace
    # propagation and the snapshot protocol are the post-mortem's
    # input, so they may neither go dark nor go stale.
    fo_files = ("presto_tpu/serve/fleet.py",
                "presto_tpu/serve/router.py",
                "presto_tpu/obs/fleetagg.py")
    fo_events: Set[str] = set()
    fo_spans: Set[str] = set()
    fo_metrics: Set[str] = set()
    for rel in fo_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        fo_events |= set(EMIT_RE.findall(src))
        fo_events |= set(CLUSTER_EVENT_RE.findall(src))
        fo_spans |= set(SPAN_RE.findall(src))
        fo_metrics |= set(METRIC_RE.findall(src))
    for s in sorted(taxonomy.FLEET_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: FLEET_SPANS lists %r which is not in "
            "SERVE_SPANS" % s)
    for s in sorted(taxonomy.FLEET_SPANS - fo_spans):
        problems.append(
            "obs/taxonomy.py: FLEET_SPANS lists %r but the fleet "
            "obs layer never opens it" % s)
    for s in sorted({x for x in fo_spans if x.startswith("fleet:")}
                    - taxonomy.FLEET_SPANS):
        problems.append(
            "fleet obs layer: span %r is not registered in "
            "obs/taxonomy.FLEET_SPANS" % s)
    for k in sorted(taxonomy.FLEET_OBS_EVENTS
                    - taxonomy.FLEET_EVENTS):
        problems.append(
            "obs/taxonomy.py: FLEET_OBS_EVENTS lists %r which is "
            "not in FLEET_EVENTS" % k)
    for k in sorted(taxonomy.FLEET_OBS_EVENTS - fo_events):
        problems.append(
            "obs/taxonomy.py: FLEET_OBS_EVENTS lists %r but the "
            "fleet obs layer never emits it" % k)
    for k in sorted({x for x in fo_events
                     if x.startswith("fleet-obs-")
                     or x == "fleet-chaos-point"}
                    - taxonomy.FLEET_OBS_EVENTS):
        problems.append(
            "fleet obs layer: event kind %r is not registered in "
            "obs/taxonomy.FLEET_OBS_EVENTS" % k)
    for name in sorted(taxonomy.FLEET_OBS_METRICS
                       - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: FLEET_OBS_METRICS lists %r which is "
            "not in METRICS" % name)
    for name in sorted(taxonomy.FLEET_OBS_METRICS - fo_metrics):
        problems.append(
            "obs/taxonomy.py: FLEET_OBS_METRICS lists %r but the "
            "fleet obs layer never registers it" % name)
    for name in sorted({x for x in fo_metrics
                        if x.startswith("fleet_obs_")
                        or x == "job_e2e_seconds"}
                       - taxonomy.FLEET_OBS_METRICS):
        problems.append(
            "fleet obs layer: metric %r is not registered in "
            "obs/taxonomy.FLEET_OBS_METRICS" % name)

    # 14. the SLO observatory (obs/slo.py + serve/jobledger.py +
    # serve/router.py): SLO_METRICS / SLO_EVENTS / SLO_SPANS pinned
    # BOTH directions + subset-of-parent — the usage metering at the
    # fence-checked commit and the burn/scale decision signals are
    # the contract future control-plane PRs inherit.
    slo_files = ("presto_tpu/obs/slo.py",
                 "presto_tpu/serve/jobledger.py",
                 "presto_tpu/serve/router.py")
    sl_events: Set[str] = set()
    sl_spans: Set[str] = set()
    sl_metrics: Set[str] = set()
    for rel in slo_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        sl_events |= set(EMIT_RE.findall(src))
        sl_events |= set(CLUSTER_EVENT_RE.findall(src))
        sl_spans |= set(SPAN_RE.findall(src))
        sl_metrics |= set(METRIC_RE.findall(src))
    for s in sorted(taxonomy.SLO_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: SLO_SPANS lists %r which is not in "
            "SERVE_SPANS" % s)
    for s in sorted(taxonomy.SLO_SPANS - sl_spans):
        problems.append(
            "obs/taxonomy.py: SLO_SPANS lists %r but the slo layer "
            "never opens it" % s)
    for s in sorted({x for x in sl_spans if x.startswith("slo:")}
                    - taxonomy.SLO_SPANS):
        problems.append(
            "slo layer: span %r is not registered in "
            "obs/taxonomy.SLO_SPANS" % s)
    for k in sorted(taxonomy.SLO_EVENTS - sl_events):
        problems.append(
            "obs/taxonomy.py: SLO_EVENTS lists %r but the slo layer "
            "never emits it" % k)
    for k in sorted({x for x in sl_events if x.startswith("slo-")}
                    - taxonomy.SLO_EVENTS):
        problems.append(
            "slo layer: event kind %r is not registered in "
            "obs/taxonomy.SLO_EVENTS" % k)
    for name in sorted(taxonomy.SLO_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: SLO_METRICS lists %r which is not in "
            "METRICS" % name)
    for name in sorted(taxonomy.SLO_METRICS - sl_metrics):
        problems.append(
            "obs/taxonomy.py: SLO_METRICS lists %r but the slo "
            "layer never registers it" % name)
    for name in sorted({x for x in sl_metrics
                        if x.startswith("slo_")}
                       - taxonomy.SLO_METRICS):
        problems.append(
            "slo layer: metric %r is not registered in "
            "obs/taxonomy.SLO_METRICS" % name)

    # 15. the kernel observatory (obs/costmodel.py + obs/roofline.py
    # + bench.py): COST_SPANS / COST_METRICS pinned BOTH directions
    # (and as a subset of METRICS) — the per-kind FLOP/byte dispatch
    # join is the measurement rig every remaining perf item is judged
    # by, so it may neither go dark nor go stale.  The `obs:` span
    # prefix scopes the check (bench.py also opens bench:* spans,
    # which belong to no catalog).
    cost_files = ("presto_tpu/obs/costmodel.py",
                  "presto_tpu/obs/roofline.py",
                  "bench.py")
    co_spans: Set[str] = set()
    co_metrics: Set[str] = set()
    for rel in cost_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        co_spans |= set(SPAN_RE.findall(src))
        co_metrics |= set(METRIC_RE.findall(src))
    for name in sorted(taxonomy.COST_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: COST_METRICS lists %r which is not in "
            "METRICS" % name)
    for s in sorted(taxonomy.COST_SPANS
                    - {x for x in co_spans if x.startswith("obs:")}):
        problems.append(
            "obs/taxonomy.py: COST_SPANS lists %r but the cost layer "
            "never opens it" % s)
    for s in sorted({x for x in co_spans if x.startswith("obs:")}
                    - taxonomy.COST_SPANS):
        problems.append(
            "cost layer: span %r is not registered in "
            "obs/taxonomy.COST_SPANS" % s)
    for name in sorted(taxonomy.COST_METRICS - co_metrics):
        problems.append(
            "obs/taxonomy.py: COST_METRICS lists %r but the cost "
            "layer never registers it" % name)
    for name in sorted({x for x in co_metrics
                        if x.startswith("kernel_")
                        or x.startswith("cost_model_")}
                       - taxonomy.COST_METRICS):
        problems.append(
            "cost layer: metric %r is not registered in "
            "obs/taxonomy.COST_METRICS" % name)

    # 16. the fleet supervisor (serve/supervisor.py + serve/router.py
    # + serve/jobledger.py): SUPERVISOR_EVENTS / SUPERVISOR_SPANS /
    # SUPERVISOR_METRICS pinned BOTH directions (and as subsets of
    # their parent catalogs) — every spawn/drain/hold decision must be
    # reconstructable from telemetry alone, so the actuation loop's
    # vocabulary may neither go dark nor go stale.
    sup_files = ("presto_tpu/serve/supervisor.py",
                 "presto_tpu/serve/router.py",
                 "presto_tpu/serve/jobledger.py")
    su_events: Set[str] = set()
    su_spans: Set[str] = set()
    su_metrics: Set[str] = set()
    for rel in sup_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        su_events |= set(EMIT_RE.findall(src))
        su_events |= set(CLUSTER_EVENT_RE.findall(src))
        su_spans |= set(SPAN_RE.findall(src))
        su_metrics |= set(METRIC_RE.findall(src))
    for s in sorted(taxonomy.SUPERVISOR_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: SUPERVISOR_SPANS lists %r which is not "
            "in SERVE_SPANS" % s)
    for s in sorted(taxonomy.SUPERVISOR_SPANS - su_spans):
        problems.append(
            "obs/taxonomy.py: SUPERVISOR_SPANS lists %r but the "
            "supervisor layer never opens it" % s)
    for s in sorted({x for x in su_spans
                     if x.startswith("supervisor:")}
                    - taxonomy.SUPERVISOR_SPANS):
        problems.append(
            "supervisor layer: span %r is not registered in "
            "obs/taxonomy.SUPERVISOR_SPANS" % s)
    for k in sorted(taxonomy.SUPERVISOR_EVENTS - su_events):
        problems.append(
            "obs/taxonomy.py: SUPERVISOR_EVENTS lists %r but the "
            "supervisor layer never emits it" % k)
    for k in sorted({x for x in su_events
                     if x.startswith("supervisor-")}
                    - taxonomy.SUPERVISOR_EVENTS):
        problems.append(
            "supervisor layer: event kind %r is not registered in "
            "obs/taxonomy.SUPERVISOR_EVENTS" % k)
    for name in sorted(taxonomy.SUPERVISOR_METRICS
                       - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: SUPERVISOR_METRICS lists %r which is "
            "not in METRICS" % name)
    for name in sorted(taxonomy.SUPERVISOR_METRICS - su_metrics):
        problems.append(
            "obs/taxonomy.py: SUPERVISOR_METRICS lists %r but the "
            "supervisor layer never registers it" % name)
    for name in sorted({x for x in su_metrics
                        if x.startswith("supervisor_")}
                       - taxonomy.SUPERVISOR_METRICS):
        problems.append(
            "supervisor layer: metric %r is not registered in "
            "obs/taxonomy.SUPERVISOR_METRICS" % name)

    # 17. the campaign engine (serve/campaign.py + serve/router.py +
    # serve/supervisor.py): CAMPAIGN_EVENTS / CAMPAIGN_SPANS /
    # CAMPAIGN_METRICS pinned BOTH directions (and as subsets of
    # their parent catalogs) — a whole archive campaign (every wave,
    # settle, yield change, and preemption) must be reconstructable
    # from campaign_events.jsonl + spans + metrics alone, so the
    # vocabulary may neither go dark nor go stale.  The supervisor's
    # preempt pacer deliberately speaks campaign-prefixed telemetry
    # (it actuates the campaign's preemption mode), hence the
    # cross-file gather.
    camp_files = ("presto_tpu/serve/campaign.py",
                  "presto_tpu/serve/router.py",
                  "presto_tpu/serve/supervisor.py")
    ca_events: Set[str] = set()
    ca_spans: Set[str] = set()
    ca_metrics: Set[str] = set()
    for rel in camp_files:
        try:
            src = _read(rel, root)
        except OSError:
            continue
        ca_events |= set(EMIT_RE.findall(src))
        ca_events |= set(CLUSTER_EVENT_RE.findall(src))
        ca_spans |= set(SPAN_RE.findall(src))
        ca_metrics |= set(METRIC_RE.findall(src))
    for s in sorted(taxonomy.CAMPAIGN_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: CAMPAIGN_SPANS lists %r which is not "
            "in SERVE_SPANS" % s)
    for s in sorted(taxonomy.CAMPAIGN_SPANS - ca_spans):
        problems.append(
            "obs/taxonomy.py: CAMPAIGN_SPANS lists %r but the "
            "campaign layer never opens it" % s)
    for s in sorted({x for x in ca_spans
                     if x.startswith("campaign:")}
                    - taxonomy.CAMPAIGN_SPANS):
        problems.append(
            "campaign layer: span %r is not registered in "
            "obs/taxonomy.CAMPAIGN_SPANS" % s)
    for k in sorted(taxonomy.CAMPAIGN_EVENTS - ca_events):
        problems.append(
            "obs/taxonomy.py: CAMPAIGN_EVENTS lists %r but the "
            "campaign layer never emits it" % k)
    for k in sorted({x for x in ca_events
                     if x.startswith("campaign-")}
                    - taxonomy.CAMPAIGN_EVENTS):
        problems.append(
            "campaign layer: event kind %r is not registered in "
            "obs/taxonomy.CAMPAIGN_EVENTS" % k)
    for name in sorted(taxonomy.CAMPAIGN_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: CAMPAIGN_METRICS lists %r which is "
            "not in METRICS" % name)
    for name in sorted(taxonomy.CAMPAIGN_METRICS - ca_metrics):
        problems.append(
            "obs/taxonomy.py: CAMPAIGN_METRICS lists %r but the "
            "campaign layer never registers it" % name)
    for name in sorted({x for x in ca_metrics
                        if x.startswith("campaign_")}
                       - taxonomy.CAMPAIGN_METRICS):
        problems.append(
            "campaign layer: metric %r is not registered in "
            "obs/taxonomy.CAMPAIGN_METRICS" % name)

    # 18. the beam multiplexer (stream/beams.py): BEAM_EVENTS /
    # BEAM_SPANS / BEAM_METRICS pinned BOTH directions (and as subsets
    # of their parent catalogs), plus the three-way kill-point pin
    # (taxonomy == beams.BEAM_KILL_POINTS == testing/chaos re-export).
    # The hand-off audit trail — which replica leased which beam, what
    # it committed, why a write was fenced — must be reconstructable
    # from events + metrics alone, so the vocabulary may neither go
    # dark nor go stale.  The beam ledger declares its event kinds as
    # EV_* class attributes (the leaseledger idiom, cf. check 2b),
    # which count as emitted.
    try:
        beams_src = _read("presto_tpu/stream/beams.py", root)
    except OSError:
        beams_src = ""
    b_events = set(EMIT_RE.findall(beams_src))
    b_events |= set(EVENT_ATTR_RE.findall(beams_src))
    b_events = {k for k in b_events if k.startswith("beam-")}
    b_spans = set(SPAN_RE.findall(beams_src))
    b_metrics = {m for m in METRIC_RE.findall(beams_src)
                 if m.startswith("stream_beam")}
    b_points = set(POINT_RE.findall(beams_src))
    for k in sorted(taxonomy.BEAM_EVENTS - b_events):
        problems.append(
            "obs/taxonomy.py: BEAM_EVENTS lists %r but stream/beams.py "
            "never emits it" % k)
    for k in sorted(b_events - taxonomy.BEAM_EVENTS):
        problems.append(
            "stream/beams.py: event kind %r is not registered in "
            "obs/taxonomy.BEAM_EVENTS" % k)
    for s in sorted(taxonomy.BEAM_SPANS - taxonomy.STREAM_SPANS):
        problems.append(
            "obs/taxonomy.py: BEAM_SPANS lists %r which is not in "
            "STREAM_SPANS" % s)
    for s in sorted(taxonomy.BEAM_SPANS - b_spans):
        problems.append(
            "obs/taxonomy.py: BEAM_SPANS lists %r but stream/beams.py "
            "never opens it" % s)
    for s in sorted({x for x in b_spans if "beam" in x}
                    - taxonomy.BEAM_SPANS):
        problems.append(
            "stream/beams.py: span %r is not registered in "
            "obs/taxonomy.BEAM_SPANS" % s)
    for name in sorted(taxonomy.BEAM_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: BEAM_METRICS lists %r which is not in "
            "METRICS" % name)
    for name in sorted(taxonomy.BEAM_METRICS - b_metrics):
        problems.append(
            "obs/taxonomy.py: BEAM_METRICS lists %r but "
            "stream/beams.py never registers it" % name)
    for name in sorted(b_metrics - taxonomy.BEAM_METRICS):
        problems.append(
            "stream/beams.py: metric %r is not registered in "
            "obs/taxonomy.BEAM_METRICS" % name)
    for p in sorted(b_points - taxonomy.BEAM_KILL_POINTS):
        problems.append(
            "stream/beams.py: kill point %r is not registered in "
            "obs/taxonomy.BEAM_KILL_POINTS" % p)
    for p in sorted(taxonomy.BEAM_KILL_POINTS - b_points):
        problems.append(
            "obs/taxonomy.py: BEAM_KILL_POINTS lists %r but "
            "stream/beams.py never fires it" % p)
    try:
        from presto_tpu.stream import beams as _beams_mod
        from presto_tpu.testing import chaos as _chaos_mod
        if set(_beams_mod.BEAM_KILL_POINTS) != taxonomy.BEAM_KILL_POINTS:
            problems.append(
                "stream/beams.py: BEAM_KILL_POINTS disagrees with "
                "obs/taxonomy.BEAM_KILL_POINTS")
        if set(_chaos_mod.BEAM_KILL_POINTS) != taxonomy.BEAM_KILL_POINTS:
            problems.append(
                "testing/chaos.py: BEAM_KILL_POINTS disagrees with "
                "obs/taxonomy.BEAM_KILL_POINTS")
    except Exception as e:  # pragma: no cover - import failure is a lint
        problems.append(
            "beam kill-point pin: could not import the runtime copies "
            "(%s)" % e)

    # 19. the federation front door (serve/federation.py):
    # FED_EVENTS / FED_SPANS / FED_METRICS pinned BOTH directions (and
    # as subsets of their parent catalogs), plus the three-way
    # kill-point pin (taxonomy == federation.FED_KILL_POINTS ==
    # testing/chaos re-export).  Whole-fleet failover runs exactly
    # while a site is dying: which fleet held which placement, why a
    # job spilled, when the epoch fenced a zombie commit — all of it
    # must be reconstructable from fed_events.jsonl + spans + metrics
    # alone.  The federation ledger declares its event kinds as EV_*
    # class attributes (the leaseledger idiom, cf. checks 2b/10/18),
    # which count as emitted.
    try:
        fed_src = _read("presto_tpu/serve/federation.py", root)
    except OSError:
        fed_src = ""
    fd_events = set(EMIT_RE.findall(fed_src))
    fd_events |= set(EVENT_ATTR_RE.findall(fed_src))
    fd_events = {k for k in fd_events if k.startswith("fed-")}
    fd_spans = {s for s in SPAN_RE.findall(fed_src)
                if s.startswith("fed:")}
    fd_metrics = {m for m in METRIC_RE.findall(fed_src)
                  if m.startswith("fed_")}
    fd_points = set(POINT_RE.findall(fed_src))
    for k in sorted(taxonomy.FED_EVENTS - fd_events):
        problems.append(
            "obs/taxonomy.py: FED_EVENTS lists %r but "
            "serve/federation.py never emits it" % k)
    for k in sorted(fd_events - taxonomy.FED_EVENTS):
        problems.append(
            "serve/federation.py: event kind %r is not registered "
            "in obs/taxonomy.FED_EVENTS" % k)
    for s in sorted(taxonomy.FED_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: FED_SPANS lists %r which is not in "
            "SERVE_SPANS" % s)
    for s in sorted(taxonomy.FED_SPANS - fd_spans):
        problems.append(
            "obs/taxonomy.py: FED_SPANS lists %r but "
            "serve/federation.py never opens it" % s)
    for s in sorted(fd_spans - taxonomy.FED_SPANS):
        problems.append(
            "serve/federation.py: span %r is not registered in "
            "obs/taxonomy.FED_SPANS" % s)
    for name in sorted(taxonomy.FED_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: FED_METRICS lists %r which is not in "
            "METRICS" % name)
    for name in sorted(taxonomy.FED_METRICS - fd_metrics):
        problems.append(
            "obs/taxonomy.py: FED_METRICS lists %r but "
            "serve/federation.py never registers it" % name)
    for name in sorted(fd_metrics - taxonomy.FED_METRICS):
        problems.append(
            "serve/federation.py: metric %r is not registered in "
            "obs/taxonomy.FED_METRICS" % name)
    for p in sorted(fd_points - taxonomy.FED_KILL_POINTS):
        problems.append(
            "serve/federation.py: kill point %r is not registered "
            "in obs/taxonomy.FED_KILL_POINTS" % p)
    for p in sorted(taxonomy.FED_KILL_POINTS - fd_points):
        problems.append(
            "obs/taxonomy.py: FED_KILL_POINTS lists %r but "
            "serve/federation.py never fires it" % p)
    try:
        from presto_tpu.serve import federation as _fed_mod
        from presto_tpu.testing import chaos as _fchaos_mod
        if set(_fed_mod.FED_KILL_POINTS) != taxonomy.FED_KILL_POINTS:
            problems.append(
                "serve/federation.py: FED_KILL_POINTS disagrees "
                "with obs/taxonomy.FED_KILL_POINTS")
        if set(_fchaos_mod.FED_KILL_POINTS) \
                != taxonomy.FED_KILL_POINTS:
            problems.append(
                "testing/chaos.py: FED_KILL_POINTS disagrees with "
                "obs/taxonomy.FED_KILL_POINTS")
    except Exception as e:  # pragma: no cover - import failure is a lint
        problems.append(
            "fed kill-point pin: could not import the runtime copies "
            "(%s)" % e)

    # 20. learned candidate triage (presto_tpu/triage/ + the
    # serve/dag.py triage node + apps/triage.py): TRIAGE_EVENTS /
    # TRIAGE_SPANS / TRIAGE_METRICS pinned BOTH directions (and as
    # subsets of their parent catalogs).  Triage decides which
    # candidates are NEVER folded — a silent selection path would be
    # indistinguishable from a lost pulsar, so the learned selection
    # ("triage-score"), the heuristic degrade ("triage-fallback",
    # the poisoned-model row of ROBUSTNESS.md), and each calibration
    # run ("triage-calibrate") may neither go dark nor go stale.
    tr_srcs = dict(_tree_sources(root, "presto_tpu/triage"))
    for rel in ("presto_tpu/serve/dag.py",
                "presto_tpu/apps/triage.py"):
        try:
            tr_srcs[rel] = _read(rel, root)
        except OSError:
            pass
    tr_events: Set[str] = set()
    tr_spans: Set[str] = set()
    tr_metrics: Set[str] = set()
    for src in tr_srcs.values():
        tr_events |= {k for k in EMIT_RE.findall(src)
                      if k.startswith("triage-")}
        tr_spans |= {s for s in SPAN_RE.findall(src)
                     if s.startswith("serve:triage")}
        tr_metrics |= {m for m in METRIC_RE.findall(src)
                       if m.startswith("triage_")}
    for k in sorted(taxonomy.TRIAGE_EVENTS - tr_events):
        problems.append(
            "obs/taxonomy.py: TRIAGE_EVENTS lists %r but the triage "
            "layer never emits it" % k)
    for k in sorted(tr_events - taxonomy.TRIAGE_EVENTS):
        problems.append(
            "triage layer: event kind %r is not registered in "
            "obs/taxonomy.TRIAGE_EVENTS" % k)
    for s in sorted(taxonomy.TRIAGE_SPANS - taxonomy.SERVE_SPANS):
        problems.append(
            "obs/taxonomy.py: TRIAGE_SPANS lists %r which is not in "
            "SERVE_SPANS" % s)
    for s in sorted(taxonomy.TRIAGE_SPANS - tr_spans):
        problems.append(
            "obs/taxonomy.py: TRIAGE_SPANS lists %r but the triage "
            "layer never opens it" % s)
    for s in sorted(tr_spans - taxonomy.TRIAGE_SPANS):
        problems.append(
            "triage layer: span %r is not registered in "
            "obs/taxonomy.TRIAGE_SPANS" % s)
    for name in sorted(taxonomy.TRIAGE_METRICS - taxonomy.METRICS):
        problems.append(
            "obs/taxonomy.py: TRIAGE_METRICS lists %r which is not "
            "in METRICS" % name)
    for name in sorted(taxonomy.TRIAGE_METRICS - tr_metrics):
        problems.append(
            "obs/taxonomy.py: TRIAGE_METRICS lists %r but the triage "
            "layer never registers it" % name)
    for name in sorted(tr_metrics - taxonomy.TRIAGE_METRICS):
        problems.append(
            "triage layer: metric %r is not registered in "
            "obs/taxonomy.TRIAGE_METRICS" % name)
    return problems


_PATH_RE = re.compile(r"^((?:[\w./-]+)\.py): ")


@register("obs-coverage")
def check(tree: Tree) -> List[Finding]:
    """The coverage checks as a presto-lint family.  Runs only over a
    real on-disk repo (the contract needs obs/taxonomy.py importable);
    in-memory fixture trees skip it."""
    taxpath = os.path.join(tree.root, "presto_tpu", "obs",
                           "taxonomy.py")
    if not os.path.exists(taxpath):
        return []
    out: List[Finding] = []
    for problem in lint(tree.root):
        m = _PATH_RE.match(problem)
        path = "presto_tpu/obs/taxonomy.py"
        if m:
            cand = m.group(1)
            if cand in tree.files:
                path = cand
            elif "presto_tpu/" + cand in tree.files:
                path = "presto_tpu/" + cand
        out.append(Finding("obs-coverage", path, 0, problem))
    return out


def main(argv=None) -> int:
    problems = lint()
    if problems:
        print("obs_lint: %d instrumentation-coverage violation(s):"
              % len(problems))
        for p in problems:
            print("  - %s" % p)
        return 1
    print("obs_lint: instrumentation coverage OK "
          "(stages, kill points, serve events, job states, metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
