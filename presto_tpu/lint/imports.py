"""import-hygiene: no unused or duplicate imports.

The in-tree twin of the ``[tool.ruff]`` config in pyproject.toml
(rules F401/F811 scoped to import hygiene + unused code): the CI
container does not ship ruff, so the same invariant is enforced here
with the presto-lint machinery and exact ``file:line`` findings.

Deliberately conservative — a finding here must be a certain dead
import, never a style opinion:

* ``__init__.py`` files are exempt (imports are re-exports);
* a name listed in ``__all__`` or carrying a ``# noqa`` on the import
  line is used by definition;
* imports inside ``try:`` blocks are exempt (the repo's gate-missing-
  deps idiom);
* a name is "used" if it appears *anywhere* else in the file — AST
  loads, decorators, annotations, and even docstrings/strings (a
  word-boundary text search backstops the AST walk, so doctest and
  ``typing``-string usage never false-positives);
* a duplicate binding is flagged only when the same name is imported
  twice at the same (module) scope.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from presto_tpu.lint.core import Finding, Tree, register

CHECK = "import-hygiene"

NOQA_RE = re.compile(r"#\s*noqa", re.IGNORECASE)


def _bindings(node) -> List[tuple]:
    """(bound local name, full imported name) pairs.  `import a.b`
    and `import a.c` both bind `a` but are NOT duplicates (urllib
    submodule idiom), so duplicate detection keys on the full name."""
    out = []
    for a in node.names:
        if a.name == "*":
            continue
        bound = a.asname or a.name.split(".")[0]
        full = a.name if isinstance(node, ast.Import) \
            else "%s.%s" % (node.module, a.name)
        out.append((bound, full))
    return out


def _in_try(stack: List[ast.AST]) -> bool:
    return any(isinstance(n, ast.Try) for n in stack)


@register(CHECK)
def check(tree: Tree) -> List[Finding]:
    out: List[Finding] = []
    for sf in tree.under("presto_tpu/", "tools/"):
        if sf.tree is None or sf.path.endswith("__init__.py"):
            continue
        # module-level imports with their guarding context
        imports: Dict[str, List[int]] = {}   # bound name -> [linenos]
        fulls: Dict[tuple, List[int]] = {}   # (bound, full) -> lines
        exempt: set = set()

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    if isinstance(child, ast.ImportFrom) \
                            and (child.module == "__future__"
                                 or child.module is None):
                        continue
                    for name, full in _bindings(child):
                        imports.setdefault(name, []).append(
                            child.lineno)
                        fulls.setdefault((name, full), []).append(
                            child.lineno)
                        if _in_try(stack + [node]) \
                                or NOQA_RE.search(
                                    sf.line_at(child.lineno)):
                            exempt.add(name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef, ast.Lambda)):
                    continue       # function-local imports: scoped,
                    #                cheap, and often lazy by design
                else:
                    walk(child, stack + [node])

        walk(sf.tree, [])
        if not imports:
            continue
        # names used anywhere outside import statements
        import_lines = {ln for lns in imports.values() for ln in lns}
        used: set = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Name) \
                    and node.lineno not in import_lines:
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass                       # root is a Name node too
        # __all__ entries count as used
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        for c in ast.walk(node.value):
                            if isinstance(c, ast.Constant) \
                                    and isinstance(c.value, str):
                                used.add(c.value)
        for (name, full), lines in sorted(fulls.items()):
            if len(lines) > 1 and name not in exempt:
                out.append(Finding(
                    CHECK, sf.path, lines[-1],
                    "%r is imported more than once at module scope "
                    "(first at line %d)" % (full, lines[0])))
        for name, lines in sorted(imports.items()):
            if name in exempt or name in used or name == "_":
                continue
            # text backstop: any other mention (docstring, doctest,
            # string annotation) vetoes the finding
            pat = re.compile(r"\b%s\b" % re.escape(name))
            mentions = sum(
                1 for i, line in enumerate(sf.lines, 1)
                if i not in import_lines and pat.search(line))
            if mentions:
                continue
            out.append(Finding(
                CHECK, sf.path, lines[0],
                "%r is imported but never used (ruff F401); remove "
                "it or mark the line `# noqa` if it is a deliberate "
                "re-export" % name))
    return out
