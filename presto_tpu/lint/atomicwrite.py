"""atomic-write: artifact writers must be crash-atomic.

The survey checkpoint contract ("a stage is skipped when its outputs
already exist", docs/ROBUSTNESS.md) makes a half-written artifact a
*silent* corruption: a resume trusts whatever is on disk.  Every
artifact writer in the artifact-producing layers — ``pipeline/``,
``serve/``, ``obs/`` — must therefore either go through
`io.atomic.atomic_open` (tmp + fsync + rename) or use a recognized
equivalent idiom:

* **tmp + replace**: the enclosing function also calls
  ``os.replace``/``os.rename`` — the open target is a staging file
  that never becomes the artifact except atomically
  (pipeline/driftprep.py's streamed rewrite used this before moving
  to atomic_open);
* **fence-staged**: the enclosing function stages via
  ``tempfile.mkstemp``/``NamedTemporaryFile`` and hands the staged
  path to a ledger ``complete()``/``complete_and_expand()`` — the
  rename happens inside the fence-checked commit transaction
  (serve/fleet.py's result staging), which is *stronger* than a local
  rename because a zombie's staged file is deleted instead of landed.

Flagged patterns: ``open(path, "w"/"wb")``, ``os.fdopen(fd,
"w"/"wb")``, and ``ndarray.tofile(<path-like>)``.  Read modes and
append-only logs (``"a"`` — the serve event JSONL, where a torn tail
line is detected by the parser) are out of scope by design.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from presto_tpu.lint.core import (Finding, Tree, call_name,
                                  function_scopes, register, str_const)

CHECK = "atomic-write"

#: layers whose writes are survey/serve artifacts (io/ itself hosts
#: the atomic writer; apps/ CLIs write user-addressed files through
#: io-layer writers that are covered transitively)
SCOPES = ("presto_tpu/pipeline/", "presto_tpu/serve/",
          "presto_tpu/obs/", "presto_tpu/stream/",
          "presto_tpu/tune/", "presto_tpu/triage/")

WRITE_MODES = ("w", "wb", "w+", "wb+", "wt")

#: atomic replacement primitives recognized inside the enclosing
#: function
REPLACE_CALLS = {"os.replace", "os.rename"}
STAGE_CALLS = {"tempfile.mkstemp", "tempfile.NamedTemporaryFile",
               "mkstemp", "NamedTemporaryFile"}
FENCE_ATTRS = {"complete", "complete_and_expand"}


def _write_mode(call: ast.Call) -> Optional[str]:
    """The constant write mode of an open()/os.fdopen() call, or
    None when the call is not a flagged writer."""
    name = call_name(call)
    if name == "open" or name == "os.fdopen" or name == "fdopen":
        mode = None
        if len(call.args) >= 2:
            mode = str_const(call.args[1])
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = str_const(kw.value)
        if mode in WRITE_MODES:
            return mode
    return None


def _path_like(node: ast.AST, path_names=frozenset()) -> bool:
    """Is this .tofile() argument a filesystem path (vs an already-
    managed file object)?  Conservative: constants, f-strings, str
    concatenation, os.path.join(), and local names assigned from one
    of those count; anything else is presumed a file object."""
    if str_const(node) is not None or isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp):
        return _path_like(node.left, path_names) \
            or _path_like(node.right, path_names)
    if isinstance(node, ast.Call):
        return call_name(node) in ("os.path.join", "str")
    if isinstance(node, ast.Name):
        return node.id in path_names
    return False


def _local_path_names(scope) -> frozenset:
    """Names assigned a path-like expression anywhere in the scope."""
    out = set()
    for node in ast.walk(scope.node):
        if isinstance(node, ast.Assign) and _path_like(node.value):
            out |= {t.id for t in node.targets
                    if isinstance(t, ast.Name)}
    return frozenset(out)


def _scope_has_atomic_idiom(scope) -> bool:
    names = {call_name(c) for c in scope.calls}
    if names & REPLACE_CALLS:
        return True                       # tmp + os.replace idiom
    attrs = {c.func.attr for c in scope.calls
             if isinstance(c.func, ast.Attribute)}
    if (names & STAGE_CALLS) and (attrs & FENCE_ATTRS):
        return True                       # fence-staged commit idiom
    return False


def _module_scope(sf):
    """Pseudo-scope owning calls outside any function (script-level
    writers count too)."""
    from presto_tpu.lint.core import FunctionScope
    scopes = function_scopes(sf)
    owned = {id(c) for s in scopes for c in s.calls}
    mod = FunctionScope(sf.tree, "<module>")
    mod.calls = [n for n in ast.walk(sf.tree)
                 if isinstance(n, ast.Call) and id(n) not in owned]
    return scopes + [mod]


@register(CHECK)
def check(tree: Tree) -> List[Finding]:
    out: List[Finding] = []
    for sf in tree.under(*SCOPES):
        if sf.tree is None:
            continue
        for scope in _module_scope(sf):
            idiom = _scope_has_atomic_idiom(scope)
            path_names = _local_path_names(scope)
            for call in scope.calls:
                mode = _write_mode(call)
                if mode is not None and not idiom:
                    out.append(Finding(
                        CHECK, sf.path, call.lineno,
                        "%s(..., %r) writes an artifact without "
                        "crash-atomicity in %s — use "
                        "io.atomic.atomic_open (or stage via tmp + "
                        "os.replace / a ledger fence commit); a "
                        "killed process leaves a half-written file "
                        "a resume will trust"
                        % (call_name(call), mode, scope.qualname)))
                    continue
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "tofile"
                        and call.args
                        and _path_like(call.args[0], path_names)
                        and not idiom):
                    out.append(Finding(
                        CHECK, sf.path, call.lineno,
                        ".tofile(<path>) in %s bypasses atomic "
                        "replacement — write through a file object "
                        "from io.atomic.atomic_open instead"
                        % scope.qualname))
    return out
