"""Measure the fair CPU baseline for bench.py's metrics on THIS host.

The reference's hot loops are multithreaded (OpenMP, src/Makefile:76-90:
accelsearch correlation rows accel_utils.c:1003-1014, dedispersion inner
loop dispersion.c:194-198).  Its CPU build is not buildable here (no
FFTW/CFITSIO), so the baseline is the same algorithms in NumPy +
scipy.fft (pocketfft) using EVERY host core (scipy.fft workers +
BLAS/pocketfft threading) — `search_ref` is algorithm-identical to the
device search and to accel_utils.c's loop, at the reference's float32
precision.

Writes cpu_baseline.json; bench.py reads it so the claimed vs_baseline
ratio always refers to a measured, methodology-documented number.  Run
on any new host:  python bench_cpu.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# Force the CPU backend authoritatively: the ambient environment pins
# JAX_PLATFORMS=axon and its sitecustomize re-asserts it, so setdefault
# is not enough — the config update below is (same trick as tests/
# conftest.py).  The config-3/SP twins run jax-backed code and MUST
# measure the host, not the chip.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np

# single source for the bench workload and input data
from bench import ACCEL_T, WORKLOAD, make_accel_input


def bench_accel_cpu(repeats=2):
    """Config-4 analog: accelsearch zmax=200 numharm=8 over 2^21 bins —
    identical data, config, and search scope to bench.py's device run."""
    from presto_tpu.search.accel import AccelConfig
    from presto_tpu.search.accel_ref import timed_search_ref

    T = ACCEL_T
    pairs = make_accel_input()
    cfg = AccelConfig(zmax=WORKLOAD["accel_zmax"],
                      numharm=WORKLOAD["accel_numharm"], sigma=6.0)

    best = float("inf")
    cells = ncands = 0
    for _ in range(repeats):
        cands, t_plane, t_search, cells = timed_search_ref(
            pairs, cfg, T, dtype=np.float32)
        best = min(best, t_plane + t_search)
        ncands = len(cands)
    return {"cells_per_sec": cells / best, "seconds": best,
            "cells": cells, "ncands": ncands}


def bench_dedisp_cpu(repeats=3):
    """Config-2 analog, compute only: 128 chans -> 32 subbands once,
    then 128 DM trials of subband shift-and-sum over 2^20 samples
    (dedisp_subbands + float_dedisp, dispersion.c:165-229), vectorized
    slice-adds over the full in-memory series (the fastest plain-NumPy
    formulation: memory-bandwidth-bound, like the reference's loop)."""
    numchan, nsub, numdms, N = (WORKLOAD["dedisp_numchan"],
                                WORKLOAD["dedisp_nsub"],
                                WORKLOAD["dedisp_numdms"],
                                WORKLOAD["dedisp_nsamples"])
    rng = np.random.default_rng(1)
    raw = rng.normal(size=(numchan, N)).astype(np.float32)
    # linear-ish delay ladders (magnitudes match a 0-250 pc/cc plan)
    chan_delays = (np.arange(numchan) * 2).astype(np.int64)
    dm_delays = (np.arange(numdms)[:, None] *
                 np.linspace(0, 12, nsub)[None, :]).astype(np.int64)
    maxd = int(chan_delays.max())
    maxdd = int(dm_delays.max())
    out_len = N - maxd - maxdd

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        sub = np.zeros((nsub, N - maxd), dtype=np.float32)
        per = numchan // nsub
        for c in range(numchan):
            sub[c // per] += raw[c, chan_delays[c]:chan_delays[c] + N - maxd]
        out = np.zeros((numdms, out_len), dtype=np.float32)
        for s in range(nsub):
            row = sub[s]
            for d in range(numdms):
                off = dm_delays[d, s]
                out[d] += row[off:off + out_len]
        checksum = float(out[:, ::4096].sum())
        best = min(best, time.perf_counter() - t0)
    return {"dm_trials_per_sec": numdms / best, "seconds": best,
            "numdms": numdms, "nsamples": N, "checksum": checksum}


def bench_accel3_cpu():
    """Config-3 CPU twin: search_ref (zmax=0 nh=16 sigma=2) + the
    SAME batched polish algorithm on the CPU backend — conservative
    for the ratio: the reference's actual per-candidate simplex loop
    (optimize_accelcand, ~70 ms/candidate measured on this host)
    would be ~10-20x slower than this on survey candidate counts."""
    from presto_tpu.search.accel import AccelConfig
    from presto_tpu.search.accel_ref import timed_search_ref
    from presto_tpu.search.accel import (AccelSearch,
                                         eliminate_harmonics,
                                         remove_duplicates)
    from presto_tpu.search.polish import optimize_accelcands

    pairs = make_accel_input()
    numbins = WORKLOAD["accel_numbins"]
    cfg = AccelConfig(zmax=0, numharm=WORKLOAD["accel3_numharm"],
                      sigma=WORKLOAD["accel3_sigma"])
    s = AccelSearch(cfg, T=ACCEL_T, numbins=numbins)
    amps = pairs[..., 0].astype(np.complex64) + 1j * pairs[..., 1]
    t0 = time.perf_counter()
    cands, t_plane, t_search, cells = timed_search_ref(
        pairs, cfg, ACCEL_T, dtype=np.float32)
    kept = remove_duplicates(eliminate_harmonics(cands))
    ocs = optimize_accelcands(amps, kept, ACCEL_T, s.numindep,
                              with_props=False)
    el = time.perf_counter() - t0
    return {"config3_seconds": el, "config3_ncands": len(kept)}


def bench_sp_cpu():
    """Config-5 SP-stage CPU twin: the identical batched matched
    filter (search_many) on the CPU backend, all cores, over the
    SHARED series (bench.make_sp_series — twins cannot drift)."""
    from bench import make_sp_series
    from presto_tpu.search.singlepulse import SinglePulseSearch
    nf = WORKLOAD["sp_nseries"]
    series = make_sp_series()
    sp = SinglePulseSearch(threshold=WORKLOAD["sp_threshold"])
    t0 = time.perf_counter()
    res = sp.search_many(series, dt=8.192e-5,
                         dms=list(np.arange(nf, dtype=float)))
    el = time.perf_counter() - t0
    return {"sp_seconds": el,
            "sp_nevents": sum(len(c) for (c, _s, _b) in res)}


def bench_jerk_cpu():
    """Jerk-search CPU twin (VERDICT r4 weak #4): per-w plane builds
    + staged search via accel_ref.timed_jerk_ref — CONSERVATIVE (its
    docstring: subharmonic sums read the same-w plane, so the true
    reference would be slower and every device ratio derived from
    this number is a lower bound).  Kernel banks are untimed on both
    sides."""
    from presto_tpu.search.accel import AccelConfig
    from presto_tpu.search.accel_ref import timed_jerk_ref

    numbins = WORKLOAD["jerk_numbins"]
    rng = np.random.default_rng(11)
    pairs = np.stack([rng.normal(size=numbins), rng.normal(
        size=numbins)], -1).astype(np.float32)
    pairs[123456] = (200.0, 0.0)
    cfg = AccelConfig(zmax=WORKLOAD["jerk_zmax"],
                      wmax=WORKLOAD["jerk_wmax"],
                      numharm=WORKLOAD["jerk_numharm"], sigma=6.0)
    n, el, cells = timed_jerk_ref(pairs, cfg, ACCEL_T,
                                  dtype=np.float32)
    return {"jerk_seconds": el, "jerk_cells": cells,
            "jerk_ncands": n}


def bench_prepdata_cpu(repeats=3):
    """Config-1 twin: single-DM shift-and-sum of 128 chans to one
    series (prepdata's compute core, dispersion.c:125-161 semantics),
    vectorized slice adds — memory-bandwidth-bound like the C loop."""
    from bench import make_prep_delays
    numchan, N = WORKLOAD["prep_numchan"], WORKLOAD["prep_nsamples"]
    rng = np.random.default_rng(5)
    raw = rng.normal(size=(numchan, N)).astype(np.float32)
    bins = np.asarray(make_prep_delays(), np.int64)
    out_len = N - int(bins.max())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = np.zeros(out_len, np.float32)
        for c in range(numchan):
            out += raw[c, bins[c]:bins[c] + out_len]
        checksum = float(out[::4096].sum())
        best = min(best, time.perf_counter() - t0)
    return {"prep_seconds": best, "prep_samples_per_sec": N / best,
            "prep_checksum": checksum}


def main():
    import scipy

    t0 = time.time()
    accel = bench_accel_cpu()
    dedisp = bench_dedisp_cpu()
    accel3 = bench_accel3_cpu()
    spb = bench_sp_cpu()
    jerk = bench_jerk_cpu()
    prep = bench_prepdata_cpu()
    out = {
        # workload fingerprint: bench.py validates this against its
        # own config so the TPU/CPU ratio can never silently compare
        # different workloads (drift guard)
        "workload": WORKLOAD,
        "accel_cells_per_sec": round(accel["cells_per_sec"], 1),
        "accel_seconds": round(accel["seconds"], 3),
        "accel_ncands": accel["ncands"],
        "dedisp_dm_trials_per_sec": round(dedisp["dm_trials_per_sec"], 2),
        "dedisp_seconds": round(dedisp["seconds"], 3),
        "config3_seconds": round(accel3["config3_seconds"], 2),
        "config3_ncands": accel3["config3_ncands"],
        "sp_seconds": round(spb["sp_seconds"], 2),
        "sp_nevents": spb["sp_nevents"],
        "jerk_seconds": round(jerk["jerk_seconds"], 2),
        "jerk_cells": jerk["jerk_cells"],
        "jerk_ncands": jerk["jerk_ncands"],
        "prep_seconds": round(prep["prep_seconds"], 4),
        "prep_samples_per_sec": round(prep["prep_samples_per_sec"], 1),
        "nproc": os.cpu_count(),
        "numpy": np.__version__,
        "scipy": scipy.__version__,
        "measured_unix": int(time.time()),
        "methodology": (
            "search_ref (algorithm-identical to accel_utils.c:1002-1051 "
            "and the device path) at float32 via scipy.fft pocketfft with "
            "workers=all cores; dedisp = vectorized NumPy shift-and-sum "
            "(dispersion.c:165-229 semantics), 128 chan -> 32 subbands -> "
            "128 DMs x 2^20 samples; best-of-N wall time on this host. "
            "NOTE: this shared host shows up to ~2.7x CPU run-to-run "
            "variance; the file keeps the fastest (strongest) CPU "
            "observed per metric — conservative for every TPU ratio"),
    }
    # Keep the FASTEST CPU ever observed per metric: this shared host
    # shows up to ~2.7x run-to-run CPU variance (noisy neighbors), and
    # the strongest CPU baseline is the conservative one for every
    # claimed TPU ratio.  Merged only when the relevant workload keys
    # match (new keys may extend the fingerprint).
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    # metric GROUPS merge atomically (seconds decide; derived rates
    # and counts ride along so the file never mixes runs into a
    # self-inconsistent pair)
    GROUPS = (
        ("accel_seconds", ("accel_cells_per_sec", "accel_ncands")),
        ("dedisp_seconds", ("dedisp_dm_trials_per_sec",)),
        ("config3_seconds", ("config3_ncands",)),
        ("sp_seconds", ("sp_nevents",)),
        ("jerk_seconds", ("jerk_cells", "jerk_ncands")),
        ("prep_seconds", ("prep_samples_per_sec",)),
    )
    try:
        with open(path) as f:
            old = json.load(f)
    except FileNotFoundError:
        old = None
    except json.JSONDecodeError as e:
        print("# previous cpu_baseline.json unreadable (%s) — NOT "
              "merging; the conservative-best policy restarts from "
              "this run" % e, file=sys.stderr)
        old = None
    if old is not None:
        ow = old.get("workload") or {}
        shared = [k2 for k2 in WORKLOAD if k2 in ow]
        same_env = all(old.get(k2) == out[k2]
                       for k2 in ("nproc", "numpy", "scipy"))
        if not same_env:
            print("# environment changed vs previous baseline — NOT "
                  "merging (provenance would misattribute old "
                  "timings)", file=sys.stderr)
        elif shared and all(ow[k2] == WORKLOAD[k2] for k2 in shared):
            for secs_key, riders in GROUPS:
                if old.get(secs_key, float("inf")) < out[secs_key]:
                    out[secs_key] = old[secs_key]
                    for rk in riders:
                        if rk in old:
                            out[rk] = old[rk]
            print("# merged with previous baseline (per-group best; "
                  "host CPU varies run-to-run)", file=sys.stderr)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print("# total bench_cpu time %.1fs" % (time.time() - t0),
          file=sys.stderr)


if __name__ == "__main__":
    main()
