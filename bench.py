"""Benchmark: accelsearch + dedispersion throughput on the current device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Headline metric: F-Fdot cells/sec for a zmax=200, numharm=8 in-core
search over a 2^21-bin spectrum (BASELINE.md config 4 analog).  A
"cell" is one fundamental-plane (z, r) power: numz * numr_halfbins,
divided by the full search wall time (plane build + harmonic sums +
thresholding + host candidate collection), steady-state, with the
spectrum DEVICE-RESIDENT (the survey path keeps spectra in HBM; the
CPU baseline's data is likewise already in RAM).  The inclusive
number (fresh host upload each run — dominated by this link's tunnel,
negligible on PCIe) is reported alongside as
inclusive_cells_per_sec.

Secondary metric (extra keys on the same line): DM-trials/sec of the
device dedispersion pipeline (BASELINE.md config 2 analog, compute
only: 128 chans -> 32 subbands -> 128 DMs x 2^20 samples, data
resident, a checksum scalar forces execution — the output of this
stage feeds the on-device FFT in the real pipeline, so compute-only is
the relevant rate; BASELINE.md documents the transfer-bound end-to-end
numbers for this tunneled link separately).

vs_baseline ratios compare against cpu_baseline.json, measured on this
host by bench_cpu.py: the identical algorithms (search_ref is
algorithm-identical to the device path and to accel_utils.c:1002-1051)
in NumPy/scipy.fft using every host core — standing in for the
unbuildable FFTW/OpenMP reference build.  Fallback constants are the
last measured values for this host.
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# Fallbacks if cpu_baseline.json is absent (measured 2026-07, 1-core host)
FALLBACK_CPU_CELLS_PER_SEC = 2.89e7
FALLBACK_CPU_DM_TRIALS_PER_SEC = 41.2


# the workload both bench scripts must run for ratios to be comparable;
# cpu_baseline.json carries the same fingerprint (drift guard)
WORKLOAD = {"accel_numbins": 1 << 21, "accel_zmax": 200,
            "accel_numharm": 8, "dedisp_numchan": 128,
            "dedisp_nsub": 32, "dedisp_numdms": 128,
            "dedisp_nsamples": 1 << 20}


def load_cpu_baseline():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    try:
        with open(path) as f:
            b = json.load(f)
        if b.get("workload") != WORKLOAD:
            print("# cpu_baseline.json workload mismatch — re-run "
                  "bench_cpu.py; using fallback constants",
                  file=sys.stderr)
            return (FALLBACK_CPU_CELLS_PER_SEC,
                    FALLBACK_CPU_DM_TRIALS_PER_SEC, None)
        return (float(b["accel_cells_per_sec"]),
                float(b["dedisp_dm_trials_per_sec"]), b)
    except Exception:
        return FALLBACK_CPU_CELLS_PER_SEC, FALLBACK_CPU_DM_TRIALS_PER_SEC, None


ACCEL_T = 1000.0


def make_accel_input():
    """The exact accel-bench spectrum BOTH bench scripts must search
    (part of the workload contract, like WORKLOAD): noise + a few
    injected tones to exercise candidate paths."""
    numbins = WORKLOAD["accel_numbins"]
    rng = np.random.default_rng(42)
    re = rng.normal(size=numbins).astype(np.float32)
    im = rng.normal(size=numbins).astype(np.float32)
    pairs = np.stack([re, im], -1)
    for r0 in (12345, 123456, 765432):
        pairs[r0] = (300.0, 0.0)
    return pairs


def bench_accel():
    import jax
    import jax.numpy as jnp
    from presto_tpu.search.accel import AccelConfig, AccelSearch

    numbins = WORKLOAD["accel_numbins"]
    T = ACCEL_T
    pairs = make_accel_input()
    cfg = AccelConfig(zmax=WORKLOAD["accel_zmax"],
                      numharm=WORKLOAD["accel_numharm"], sigma=6.0)
    s = AccelSearch(cfg, T=T, numbins=numbins)

    t0 = time.time()
    cands = s.search(pairs)          # warmup (compile or cache load)
    warm = time.time() - t0

    # inclusive: fresh host upload every run (transfer-bound here)
    incl = float("inf")
    for _ in range(3):
        t0 = time.time()
        cands = s.search(pairs)
        incl = min(incl, time.time() - t0)

    # device-resident steady state (the survey fused path's regime):
    # best of 5, the tunneled chip shows 20-30% run-to-run variance
    dev_pairs = jnp.asarray(pairs)
    float(dev_pairs.sum())           # settle the upload
    elapsed = float("inf")
    for _ in range(5):
        t0 = time.time()
        cands = s.search(dev_pairs)
        elapsed = min(elapsed, time.time() - t0)

    # diagnostic: the 16 MB H2D spectrum upload cost through the
    # tunneled link — a separate reference measurement, min-of-2 so
    # the probe's own compile doesn't count
    upload = float("inf")
    for _ in range(2):
        t0 = time.time()
        float(jnp.asarray(pairs).sum())
        upload = min(upload, time.time() - t0)

    numr = int(s.rhi - s.rlo) * 2
    cells = cfg.numz * numr
    return (cells / elapsed, warm, elapsed, cells, len(cands), upload,
            cells / incl, incl)


def bench_dedisp():
    """Compute-only DM-trials/s: data synthesized on device (nothing
    crosses the tunneled link), checksum scalar fetched to time real
    execution (block_until_ready is unreliable through the tunnel)."""
    import jax
    import jax.numpy as jnp
    from presto_tpu.ops.dedispersion import dedisperse_scan

    numchan, nsub, numdms = (WORKLOAD["dedisp_numchan"],
                             WORKLOAD["dedisp_nsub"],
                             WORKLOAD["dedisp_numdms"])
    nblocks = 10
    numpts = WORKLOAD["dedisp_nsamples"] // (nblocks - 2)
    chan_delays = (np.arange(numchan) * 2).astype(np.int32)
    dm_delays = (np.arange(numdms)[:, None] *
                 np.linspace(0, 12, nsub)[None, :]).astype(np.int32)
    delays = {"chan": chan_delays, "dm": dm_delays}

    # synthesize once OUTSIDE the timed region (bench_cpu.py also
    # excludes data generation), device-resident thereafter
    blocks = jax.jit(
        lambda key: jax.random.normal(
            key, (nblocks, numchan, numpts), dtype=jnp.float32)
    )(jax.random.PRNGKey(0))
    blocks.block_until_ready()

    @jax.jit
    def run(blocks):
        out = dedisperse_scan(blocks, delays, nsub)
        return out[:, ::4096].sum()

    t0 = time.time()
    float(run(blocks))                       # warmup
    warm = time.time() - t0
    elapsed = float("inf")
    for _ in range(3):
        t0 = time.time()
        float(run(blocks))
        elapsed = min(elapsed, time.time() - t0)
    nsamples = (nblocks - 2) * numpts
    return numdms / elapsed, warm, elapsed, nsamples


def main():
    import jax

    cpu_cells, cpu_dmtrials, cpu_meta = load_cpu_baseline()
    (cells_per_sec, warm_a, steady_a, cells, ncands, upload_a,
     incl_cells_per_sec, incl_a) = bench_accel()
    dm_per_sec, warm_d, steady_d, nsamples = bench_dedisp()

    print(json.dumps({
        "metric": "ffdot_cells_per_sec_zmax200_nh8",
        "value": round(cells_per_sec, 1),
        "unit": "cells/s",
        "vs_baseline": round(cells_per_sec / cpu_cells, 2),
        # measurement-boundary marker: value/vs_baseline are DEVICE-
        # RESIDENT from round 3 on (rounds 1-2 were upload-inclusive;
        # that regime is the inclusive_* keys)
        "regime": "device-resident",
        "inclusive_cells_per_sec": round(incl_cells_per_sec, 1),
        "inclusive_vs_baseline": round(incl_cells_per_sec / cpu_cells,
                                       2),
        "upload_s": round(upload_a, 2),
        "warmup_s": round(warm_a, 1),
        "dm_trials_per_sec": round(dm_per_sec, 1),
        "dm_trials_vs_baseline": round(dm_per_sec / cpu_dmtrials, 2),
        "cpu_baseline_measured": cpu_meta is not None,
    }))
    print("# device=%s accel: warmup=%.1fs steady=%.2fs "
          "inclusive=%.2fs (16MB H2D ref transfer %.2fs) cells=%.3g "
          "cands=%d | dedisp: warmup=%.1fs steady=%.2fs (%d DMs x %d)"
          " | cpu baseline: %.3g cells/s, %.1f DM-trials/s (%s)"
          % (jax.devices()[0].platform, warm_a, steady_a, incl_a,
             upload_a, cells, ncands, warm_d, steady_d,
             WORKLOAD["dedisp_numdms"], WORKLOAD["dedisp_nsamples"],
             cpu_cells, cpu_dmtrials,
             "measured" if cpu_meta else "fallback"),
          file=sys.stderr)


if __name__ == "__main__":
    main()
