"""Benchmark: accelsearch + dedispersion throughput on the current device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Headline metric: F-Fdot cells/sec for a zmax=200, numharm=8 in-core
search over a 2^21-bin spectrum (BASELINE.md config 4 analog).  A
"cell" is one fundamental-plane (z, r) power: numz * numr_halfbins,
divided by the full search wall time (plane build + harmonic sums +
thresholding + host candidate collection), steady-state, with the
spectrum DEVICE-RESIDENT (the survey path keeps spectra in HBM; the
CPU baseline's data is likewise already in RAM).  The inclusive
number is reported alongside as inclusive_cells_per_sec: from r07 it
measures the FUSED-pipeline regime (8-bit raw ingest -> device
decode+FFT -> search with the H2D put of trial k+1 overlapped
against the search of trial k — the bytes and syncs the fused survey
actually pays, docs/PERFORMANCE.md), with the pre-fusion serial
staged number kept as inclusive_serial_cells_per_sec and an
inclusive_breakdown block attributing transfer/compile/compute/disk
shares in both regimes.

Secondary metric (extra keys on the same line): DM-trials/sec of the
device dedispersion pipeline (BASELINE.md config 2 analog, compute
only: 128 chans -> 32 subbands -> 128 DMs x 2^20 samples, data
resident, a checksum scalar forces execution — the output of this
stage feeds the on-device FFT in the real pipeline, so compute-only is
the relevant rate; BASELINE.md documents the transfer-bound end-to-end
numbers for this tunneled link separately).

vs_baseline ratios compare against cpu_baseline.json, measured on this
host by bench_cpu.py: the identical algorithms (search_ref is
algorithm-identical to the device path and to accel_utils.c:1002-1051)
in NumPy/scipy.fft using every host core — standing in for the
unbuildable FFTW/OpenMP reference build.  Fallback constants are the
last measured values for this host.
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# Fallbacks if cpu_baseline.json is absent (measured 2026-07, 1-core host)
FALLBACK_CPU_CELLS_PER_SEC = 2.89e7
FALLBACK_CPU_DM_TRIALS_PER_SEC = 41.2


# the workload both bench scripts must run for ratios to be comparable;
# cpu_baseline.json carries the same fingerprint (drift guard)
WORKLOAD = {"accel_numbins": 1 << 21, "accel_zmax": 200,
            "accel_numharm": 8, "dedisp_numchan": 128,
            "dedisp_nsub": 32, "dedisp_numdms": 128,
            "dedisp_nsamples": 1 << 20,
            # extended rows (VERDICT r3 item 4)
            "accel3_numharm": 16, "accel3_sigma": 2.0,
            "sp_nseries": 128, "sp_nsamples": 1 << 20,
            "sp_threshold": 5.0,
            "jerk_numbins": 1 << 20, "jerk_zmax": 100,
            "jerk_wmax": 300, "jerk_numharm": 4,
            # r5 rows: config-3 amortized over a DM fan-out, config-1
            # prepdata single-DM dedispersion (VERDICT r4 weak #3/#4)
            "accel3_numdms": 64,
            "prep_numchan": 128, "prep_nsamples": 1 << 22}


def load_cpu_baseline():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    try:
        with open(path) as f:
            b = json.load(f)
        if b.get("workload") != WORKLOAD:
            print("# cpu_baseline.json workload mismatch — re-run "
                  "bench_cpu.py; using fallback constants",
                  file=sys.stderr)
            return (FALLBACK_CPU_CELLS_PER_SEC,
                    FALLBACK_CPU_DM_TRIALS_PER_SEC, None)
        return (float(b["accel_cells_per_sec"]),
                float(b["dedisp_dm_trials_per_sec"]), b)
    except Exception:
        return FALLBACK_CPU_CELLS_PER_SEC, FALLBACK_CPU_DM_TRIALS_PER_SEC, None


ACCEL_T = 1000.0


def tuning_info():
    """Tuning attribution for this bench run: the device fingerprint,
    whether lookups are active, what the tuning DB holds for this
    device, and (after the benches ran) which lookups actually hit.
    BENCH_r*.json trajectories are only comparable when this block
    matches — a tuned and an untuned run of the same chip are
    different configurations."""
    from presto_tpu import tune
    info = {"enabled": tune.enabled(),
            "db_path": tune.default_db_path(),
            "fingerprint": tune.fingerprint_key(),
            "db_present": os.path.exists(tune.default_db_path()),
            "db_configs": {}, "lookups": {}}
    if info["db_present"]:
        db = tune.TuneDB.load(info["db_path"])
        if db.load_error is not None:
            info["db_load_error"] = db.load_error
        else:
            info["db_configs"] = {
                fam: {skey: rec.get("config")
                      for skey, rec in sorted(shapes.items())}
                for fam, shapes in sorted(
                    db.families(info["fingerprint"]).items())}
    return info


def make_accel_input():
    """The exact accel-bench spectrum BOTH bench scripts must search
    (part of the workload contract, like WORKLOAD): noise + a few
    injected tones to exercise candidate paths."""
    numbins = WORKLOAD["accel_numbins"]
    rng = np.random.default_rng(42)
    re = rng.normal(size=numbins).astype(np.float32)
    im = rng.normal(size=numbins).astype(np.float32)
    pairs = np.stack([re, im], -1)
    for r0 in (12345, 123456, 765432):
        pairs[r0] = (300.0, 0.0)
    return pairs


def bench_accel():
    import jax
    import jax.numpy as jnp
    from presto_tpu.search.accel import AccelConfig, AccelSearch

    numbins = WORKLOAD["accel_numbins"]
    T = ACCEL_T
    pairs = make_accel_input()
    cfg = AccelConfig(zmax=WORKLOAD["accel_zmax"],
                      numharm=WORKLOAD["accel_numharm"], sigma=6.0)
    s = AccelSearch(cfg, T=T, numbins=numbins)

    t0 = time.time()
    cands = s.search(pairs)          # warmup (compile or cache load)
    warm = time.time() - t0

    # serial staged inclusive: fresh host upload every run, spectrum
    # shipped as float32 pairs (transfer-bound here) — the pre-fusion
    # per-stage regime, kept for trajectory continuity
    incl = float("inf")
    for _ in range(3):
        t0 = time.time()
        cands = s.search(pairs)
        incl = min(incl, time.time() - t0)

    # device-resident steady state (the survey fused path's regime):
    # best of 5, the tunneled chip shows 20-30% run-to-run variance;
    # raw per-rep samples ride along so the perf ledger can keep
    # median-of-k + MAD (obs/perfledger.py)
    dev_pairs = jnp.asarray(pairs)
    float(dev_pairs.sum())           # settle the upload
    samples = []
    for _ in range(5):
        t0 = time.time()
        cands = s.search(dev_pairs)
        samples.append(time.time() - t0)
    elapsed = min(samples)

    # diagnostic: the 16 MB H2D spectrum upload cost through the
    # tunneled link — a separate reference measurement, min-of-2 so
    # the probe's own compile doesn't count
    upload = float("inf")
    for _ in range(2):
        t0 = time.time()
        float(jnp.asarray(pairs).sum())
        upload = min(upload, time.time() - t0)

    numr = int(s.rhi - s.rlo) * 2
    cells = cfg.numz * numr
    return (cells / elapsed, warm, elapsed, cells, len(cands), upload,
            cells / incl, incl, s, samples)


def bench_accel_fused_inclusive(s, compute_s, staged_upload_s,
                                staged_incl_s, warm_s, obs=None):
    """Inclusive throughput in the FUSED-pipeline regime
    (pipeline/fusion.py, docs/PERFORMANCE.md): the search input
    spectrum is produced ON DEVICE (decode -> packed real FFT) from
    the 8-bit raw ingest stream — the bytes that actually cross the
    host link in the fused survey — and the H2D put of trial k+1 is
    issued before trial k's search collects (the 2-deep in-flight
    window).  Compare the staged serial regime: float32 pairs
    uploaded synchronously per trial, each stage boundary a disk
    round-trip.

    Returns (cells/s, per-trial seconds, ncands, breakdown dict).
    The searched spectrum is the contract spectrum's time series
    quantized to 8 bits (quantization noise is ~1%% of the Gaussian
    floor per bin; the injected tones are unaffected)."""
    import jax
    import jax.numpy as jnp
    from presto_tpu.obs import (Observability, ObsConfig, costmodel,
                                jaxtel)
    from presto_tpu.ops import fftpack

    if obs is None:
        obs = Observability(ObsConfig(enabled=True))
    numbins = WORKLOAD["accel_numbins"]
    n = numbins * 2
    pairs = make_accel_input()
    spec = fftpack.np_pairs_to_complex64(pairs)
    full = np.zeros(numbins + 1, np.complex128)
    full[0] = spec[0].real                      # DC
    full[-1] = spec[0].imag                     # Nyquist
    full[1:-1] = spec[1:]
    ts = np.fft.irfft(full, n=n)
    lo, hi = float(ts.min()), float(ts.max())
    scale = (hi - lo) / 255.0 or 1.0
    raw = np.clip(np.round((ts - lo) / scale), 0, 255).astype(np.uint8)

    @jax.jit
    def ingest_fft(u8):
        x = u8.astype(jnp.float32) * jnp.float32(scale) \
            + jnp.float32(lo)
        return fftpack.realfft_packed_pairs(x)

    # warmup (compile the decode+fft; search plans are already warm)
    cands = s.search(ingest_fft(jax.device_put(raw)))
    # unit cost of the fused ingest program (kind "ingest_fft") for
    # the kernel_costs block assembled in main()
    costmodel.probe(obs, "ingest_fft", ingest_fft, raw)

    # per-trial raw transfer reference (8-bit vs the 16 MB pairs)
    t0 = time.time()
    jax.block_until_ready(jax.device_put(raw))
    u8_upload = time.time() - t0

    K = 4
    raws = [raw.copy() for _ in range(K)]       # distinct host buffers
    snap0 = jaxtel.transfer_snapshot(obs)
    root = obs.span("bench:fused-inclusive", trials=K)
    t0 = time.time()
    nxt = jax.device_put(raws[0])
    jaxtel.note_put(obs, raws[0].nbytes)
    ncands = 0
    for k in range(K):
        jaxtel.note_dispatch(obs, "ingest_fft")
        pd = ingest_fft(nxt)
        if k + 1 < K:
            nxt = jax.device_put(raws[k + 1])   # H2D k+1 overlaps
            jaxtel.note_put(obs, raws[k + 1].nbytes)  # search k
        ncands = len(s.search(pd))
    wall = time.time() - t0
    root.finish()
    snap1 = jaxtel.transfer_snapshot(obs)

    per_trial = wall / K
    numr = int(s.rhi - s.rlo) * 2
    cells = s.cfg.numz * numr

    # the staged chain's disk share: one trial's spectrum through a
    # .fft write + read-back (what every stage boundary used to pay)
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".fft", delete=True) as f:
        t0 = time.time()
        pairs.tofile(f.name)
        f.flush()
        os.fsync(f.fileno())
        _ = np.fromfile(f.name, dtype=np.float32)
        disk_s = time.time() - t0

    staged_trial = staged_incl_s + disk_s
    breakdown = {
        "fused_trial_s": round(per_trial, 4),
        "staged_trial_s": round(staged_trial, 4),
        "transfer_s": round(u8_upload, 4),
        "staged_transfer_s": round(staged_upload_s, 4),
        "compute_s": round(compute_s, 4),
        "compile_s": round(warm_s, 2),
        "disk_s": round(disk_s, 4),
        "shares_staged": {
            "transfer": round(staged_upload_s / staged_trial, 3),
            "compute": round(compute_s / staged_trial, 3),
            "disk": round(disk_s / staged_trial, 3)},
        "shares_fused": {
            "transfer": round(min(u8_upload / per_trial, 1.0), 3),
            "compute": round(min(compute_s / per_trial, 1.0), 3),
            "disk": 0.0},
        "h2d_bytes_per_trial": raw.nbytes,
        "staged_h2d_bytes_per_trial": pairs.nbytes,
        "jaxtel_put_bytes": snap1["put_bytes"] - snap0["put_bytes"],
        "jaxtel_get_bytes": snap1["get_bytes"] - snap0["get_bytes"],
    }
    return cells / per_trial, per_trial, ncands, breakdown


def bench_dedisp(obs=None):
    """Compute-only DM-trials/s: data synthesized on device (nothing
    crosses the tunneled link), checksum scalar fetched to time real
    execution (block_until_ready is unreliable through the tunnel)."""
    import jax
    import jax.numpy as jnp
    from presto_tpu.obs import costmodel, jaxtel
    from presto_tpu.ops.dedispersion import dedisperse_scan

    numchan, nsub, numdms = (WORKLOAD["dedisp_numchan"],
                             WORKLOAD["dedisp_nsub"],
                             WORKLOAD["dedisp_numdms"])
    nblocks = 10
    numpts = WORKLOAD["dedisp_nsamples"] // (nblocks - 2)
    chan_delays = (np.arange(numchan) * 2).astype(np.int32)
    dm_delays = (np.arange(numdms)[:, None] *
                 np.linspace(0, 12, nsub)[None, :]).astype(np.int32)
    delays = {"chan": chan_delays, "dm": dm_delays}

    # synthesize once OUTSIDE the timed region (bench_cpu.py also
    # excludes data generation), device-resident thereafter
    blocks = jax.jit(
        lambda key: jax.random.normal(
            key, (nblocks, numchan, numpts), dtype=jnp.float32)
    )(jax.random.PRNGKey(0))
    blocks.block_until_ready()

    @jax.jit
    def run(blocks):
        out = dedisperse_scan(blocks, delays, nsub)
        return out[:, ::4096].sum()

    t0 = time.time()
    float(run(blocks))                       # warmup
    warm = time.time() - t0
    costmodel.probe(obs, "dedisp", run, blocks)
    samples = []
    for _ in range(3):
        jaxtel.note_dispatch(obs, "dedisp")
        t0 = time.time()
        float(run(blocks))
        samples.append(time.time() - t0)
    elapsed = min(samples)
    nsamples = (nblocks - 2) * numpts
    return numdms / elapsed, warm, elapsed, nsamples, samples


def search_and_polish(s, pairs_or_dev, T):
    """Config-3 workload body shared with bench_cpu.py's CPU twin:
    search -> harmonic elimination -> dedup -> batched polish (the
    full per-trial candidate flow of the survey's workhorse pass).
    The AccelSearch is built ONCE by the caller: compiled programs
    cache per instance, so steady-state timing must reuse it."""
    from presto_tpu.search.accel import (eliminate_harmonics,
                                         remove_duplicates)
    from presto_tpu.search.polish import optimize_accelcands
    raw = s.search(pairs_or_dev)
    cands = remove_duplicates(eliminate_harmonics(raw))
    ocs = optimize_accelcands(pairs_or_dev, cands, T, s.numindep,
                              with_props=False)
    return cands, ocs


def bench_accel3():
    """Config 3 (survey workhorse): zmax=0 numharm=16 sigma=2 over the
    same 2^21-bin spectrum, INCLUDING candidate refinement — the r2-r3
    bottleneck (serial scipy polish) now runs as the batched device
    polish, so the steady wall time is device-dominated."""
    import jax.numpy as jnp
    from presto_tpu.search.accel import AccelConfig

    numbins = WORKLOAD["accel_numbins"]
    pairs = make_accel_input()
    cfg = AccelConfig(zmax=0, numharm=WORKLOAD["accel3_numharm"],
                      sigma=WORKLOAD["accel3_sigma"])
    from presto_tpu.search.accel import AccelSearch
    s = AccelSearch(cfg, T=ACCEL_T, numbins=numbins)
    dev_pairs = jnp.asarray(pairs)
    float(dev_pairs.sum())
    t0 = time.time()
    cands, _ = search_and_polish(s, dev_pairs, ACCEL_T)
    warm = time.time() - t0
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        cands, ocs = search_and_polish(s, dev_pairs, ACCEL_T)
        best = min(best, time.time() - t0)
    return best, warm, len(cands)


def make_accel3_batch():
    """The config-3 DM fan-out batch (shared workload contract):
    trial 0 is the exact single-trial config-3 spectrum, the rest are
    fresh noise with the same tone set shifted per trial (same
    candidate-count scale per trial, so per-trial cost is
    comparable)."""
    numbins, nd = WORKLOAD["accel_numbins"], WORKLOAD["accel3_numdms"]
    batch = np.empty((nd, numbins, 2), np.float32)
    batch[0] = make_accel_input()
    rng = np.random.default_rng(2025)
    for d in range(1, nd):
        re = rng.normal(size=numbins).astype(np.float32)
        im = rng.normal(size=numbins).astype(np.float32)
        batch[d] = np.stack([re, im], -1)
        for r0 in (12345, 123456, 765432):
            batch[d, r0 + 17 * d] = (300.0, 0.0)
    return batch


def bench_accel3_amortized(obs=None):
    """Config 3 the way the survey RUNS it (VERDICT r4 weak #3): one
    search_many over a WORKLOAD["accel3_numdms"]-trial DM fan-out
    (spectra device-resident,
    batched plane builds + batched scans), then per-trial candidate
    flow (eliminate/dedup + batched polish against that trial's
    spectrum).  Reported as per-trial seconds; the CPU baseline is
    the measured single-trial config-3 twin, which has no batching to
    amortize (the reference's accelsearch is likewise invoked once
    per .dat)."""
    import jax.numpy as jnp
    from presto_tpu.search.accel import (AccelConfig, AccelSearch,
                                         eliminate_harmonics,
                                         remove_duplicates)
    from presto_tpu.search.polish import optimize_accelcands_batched

    nd = WORKLOAD["accel3_numdms"]
    batch = jnp.asarray(make_accel3_batch())
    float(batch.sum())                  # settle the upload
    cfg = AccelConfig(zmax=0, numharm=WORKLOAD["accel3_numharm"],
                      sigma=WORKLOAD["accel3_sigma"])
    s = AccelSearch(cfg, T=ACCEL_T, numbins=batch.shape[1])

    def run():
        res = s.search_many(batch, obs=obs)
        kept = [remove_duplicates(eliminate_harmonics(raw))
                for raw in res]
        # cross-trial batched polish: every trial's candidates
        # against its own spectrum in ONE device pipeline (per-trial
        # calls each pay the link's ~120 ms dispatch floor)
        ocs = optimize_accelcands_batched(batch, kept, ACCEL_T,
                                          s.numindep)
        return sum(len(o) for o in ocs)

    t0 = time.time()
    n = run()                           # warmup/compile
    warm = time.time() - t0
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        n = run()
        best = min(best, time.time() - t0)
    return best / nd, warm, n, nd


def bench_prepdata():
    """Config 1 (prepdata): single-DM dedispersion of a 128-chan
    stream to one time series, compute-only and device-resident
    (the real prepdata is reader-I/O-bound; the compute rate is what
    the backend contributes — BASELINE.md documents the transfer
    story for this link separately)."""
    import jax
    import jax.numpy as jnp
    from presto_tpu.ops.dedispersion import dedisperse_series

    numchan, N = WORKLOAD["prep_numchan"], WORKLOAD["prep_nsamples"]
    bins = make_prep_delays()
    blocks = jax.jit(
        lambda key: jax.random.normal(key, (numchan, N),
                                      dtype=jnp.float32)
    )(jax.random.PRNGKey(5))
    blocks.block_until_ready()

    @jax.jit
    def run(x):
        # bins stay a NumPy array so dedisperse_series computes its
        # int(max) trim statically (a device array would force a
        # host sync at trace time); the slices themselves use the
        # same dynamic_slice path either way
        out = dedisperse_series(x, bins)
        return out[::4096].sum()

    t0 = time.time()
    float(run(blocks))
    warm = time.time() - t0
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        float(run(blocks))
        best = min(best, time.time() - t0)
    # fused-seam regime (BENCH_r05 note: the single-DM pass was
    # dispatch-floor-bound at ~0.1 s): the survey's streaming loop
    # never syncs between block dispatches, so issue K back-to-back
    # and force once — per-call wall amortizes the link's dispatch
    # floor exactly like the seam's in-flight window does
    K = 8
    t0 = time.time()
    vals = [run(blocks) for _ in range(K)]
    jax.block_until_ready(vals)
    piped = (time.time() - t0) / K
    return N / piped, warm, piped, best


def make_prep_delays():
    """Config-1 delay ladder (shared workload contract): the
    quadratic nu^-2 shape of a real DM at survey magnitudes."""
    numchan = WORKLOAD["prep_numchan"]
    c = np.arange(numchan, dtype=np.float64)
    return (4000.0 * ((numchan / (numchan + c)) ** 2
                      - (numchan / (2 * numchan)) ** 2)
            ).astype(np.int32).clip(min=0)


def make_sp_series():
    """The SP-bench series BOTH bench scripts must search (shared so
    the CPU/TPU twins cannot drift; part of the workload contract)."""
    nf, n = WORKLOAD["sp_nseries"], WORKLOAD["sp_nsamples"]
    rng = np.random.default_rng(7)
    series = [rng.normal(size=n).astype(np.float32) for _ in range(nf)]
    for s in series[::8]:           # sprinkle single pulses
        for pos in (12345, 500000):
            s[pos:pos + 30] += 4.0
    return series


def bench_singlepulse():
    """Config 5's SP stage: the device-resident batched matched
    filter over a 128-trial x 2^20-sample DM fan-out
    (search_many_resident — the survey's fused regime: the
    dedispersed series are already in HBM; only stds/scales and the
    compacted hits cross the boundary).  The CPU twin runs the full
    host search_many on the same data."""
    import jax.numpy as jnp
    from presto_tpu.search.singlepulse import SinglePulseSearch

    nf = WORKLOAD["sp_nseries"]
    series = make_sp_series()
    batch = jnp.asarray(np.stack(series))     # resident (one upload)
    float(batch.sum())
    sp = SinglePulseSearch(threshold=WORKLOAD["sp_threshold"])
    dms = list(np.arange(nf, dtype=float))
    t0 = time.time()
    res = sp.search_many_resident(batch, dt=8.192e-5, dms=dms)
    warm = time.time() - t0
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        res = sp.search_many_resident(batch, dt=8.192e-5, dms=dms)
        best = min(best, time.time() - t0)
    nev = sum(len(c) for (c, _st, _b) in res)
    return best, warm, nev


def bench_jerk():
    """Jerk-search diagnostic: zmax=100 wmax=300 numharm=4 over a
    2^20-bin spectrum, device-resident — (r, z, w) volume cells/s
    (kernel banks host-built once and cached; the reference also
    excludes its 'Generating correlation kernels' setup from the
    search loop, accelsearch.c:134-160)."""
    import jax.numpy as jnp
    from presto_tpu.search.accel import AccelConfig, AccelSearch

    numbins = WORKLOAD["jerk_numbins"]
    rng = np.random.default_rng(11)
    pairs = np.stack([rng.normal(size=numbins), rng.normal(
        size=numbins)], -1).astype(np.float32)
    pairs[123456] = (200.0, 0.0)
    cfg = AccelConfig(zmax=WORKLOAD["jerk_zmax"],
                      wmax=WORKLOAD["jerk_wmax"],
                      numharm=WORKLOAD["jerk_numharm"], sigma=6.0)
    s = AccelSearch(cfg, T=ACCEL_T, numbins=numbins)
    dev_pairs = jnp.asarray(pairs)
    float(dev_pairs.sum())
    t0 = time.time()
    cands = s.search(dev_pairs)
    warm = time.time() - t0
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        cands = s.search(dev_pairs)
        best = min(best, time.time() - t0)
    numr = int(s.rhi - s.rlo) * 2
    cells = cfg.numz * numr * len(cfg.ws)
    return cells / best, warm, best, cells, len(cands)


def bench_multichip_inclusive(fast: bool = False):
    """The MULTICHIP twin of inclusive_breakdown: fused vs staged
    INCLUSIVE throughput of the DM-sharded chain (dedisp -> rFFT ->
    accelsearch) on the current device mesh, with transfer/compile/
    compute/disk attribution.  The fused regime is the sharded seam
    (pipeline/fusion.ShardedSeamBlock): per-device static-delay
    dedispersion feeds a dm-sharded batched rFFT and a shard_map'd
    search in place, with ONE per-shard gather at candidate
    collection.  The staged regime is the pre-seam sharded contract:
    gather the fan-out to host, round-trip every trial through a
    .dat/.fft write+read, re-upload to one device, search there.
    Returns None on a single-device host (nothing to shard).

    Identical inputs, identical candidate counts both regimes (the
    byte-level proof lives in tests/test_sharded_fusion.py; this
    block measures the wall-clock and transfer shares) — emitted into
    MULTICHIP_*.json via __graft_entry__.dryrun_multichip and onto
    the bench line when the bench host is a mesh."""
    import tempfile
    import jax
    import jax.numpy as jnp
    from presto_tpu.obs import Observability, ObsConfig, jaxtel
    from presto_tpu.ops import fftpack
    from presto_tpu.parallel.mesh import dm_sharding, make_mesh
    from presto_tpu.parallel.sharded import ShardedDedispPlan
    from presto_tpu.pipeline import fusion
    from presto_tpu.search.accel import AccelConfig, AccelSearch

    ndev = len(jax.devices())
    if ndev < 2:
        return None
    obs = Observability(ObsConfig(enabled=True))
    mesh = make_mesh()
    numchan, nsub = (32, 16) if fast else (64, 32)
    numdms = 2 * ndev if fast else 8 * ndev
    blocklen = (1 << 11) if fast else (1 << 14)
    nblocks = 4 if fast else 8
    rng = np.random.default_rng(17)
    blocks = [rng.normal(size=(numchan, blocklen)).astype(np.float32)
              for _ in range(nblocks)]
    chan_d = (np.arange(numchan) % 64).astype(np.int32)
    dm_d = (np.arange(numdms)[:, None]
            * np.linspace(0, 4, nsub)[None, :]).astype(np.int32)
    plan = ShardedDedispPlan(mesh, nsub, 1, chan_d, dm_d)
    T_s = 200.0

    def dedisperse():
        prev_raw = prev_sub = None
        outs = []
        for b in blocks:
            cur = plan.put_block(b)
            if prev_raw is not None:
                if prev_sub is None:
                    prev_sub = plan.prime(prev_raw, cur)
                else:
                    prev_sub, series = plan.step(prev_raw, cur,
                                                 prev_sub)
                    outs.append(series)
            prev_raw = cur
        return plan.concat(outs)       # [numdms, T] dm-sharded

    def fft_len(cat):
        return int(cat.shape[1]) & ~1

    # ---- warmup / compile (both regimes' programs) -----------------
    t0 = time.time()
    cat = dedisperse()
    n = fft_len(cat)
    searcher = AccelSearch(AccelConfig(zmax=0, numharm=2, sigma=3.0),
                           T=T_s, numbins=n // 2)
    pairs = fusion.fused_rfft_batch(cat[:, :n], mesh=mesh)
    res = searcher.search_many(pairs, mesh=mesh)
    host0 = fusion.gather_shards(cat, obs=obs)
    sp_fft = jax.jit(jax.vmap(fftpack.realfft_packed_pairs))
    res_staged = searcher.search_many(
        np.asarray(sp_fft(jnp.asarray(host0[:, :n]))))
    compile_s = time.time() - t0

    # ---- fused sharded regime (min of 2: the virtual-mesh CPU
    # backend shows 10-20% run-to-run variance) --------------------
    snap0 = jaxtel.transfer_snapshot(obs)
    fused_s = float("inf")
    t_fgather = 0.0
    for _ in range(2):
        t0 = time.time()
        cat = dedisperse()
        pairs = fusion.fused_rfft_batch(cat[:, :n], mesh=mesh)
        res = searcher.search_many(pairs, mesh=mesh)
        tg = time.time()
        pairs_host = fusion.gather_shards(pairs, obs=obs)
        total = time.time() - t0
        if total < fused_s:
            fused_s, t_fgather = total, time.time() - tg
    snap1 = jaxtel.transfer_snapshot(obs)
    ncands_fused = sum(len(c) for c in res)

    # ---- staged sharded regime (pre-seam contract): every trial
    # round-trips through an ATOMIC .dat (tmp+fsync+rename — what
    # io/atomic pays for every staged artifact), then re-uploads to
    # one device and searches there ----------------------------------
    staged_s = float("inf")
    t_dedisp = t_gather = t_disk = t_upload = t_search = 0.0
    for _ in range(2):
        t0 = time.time()
        cat = dedisperse()
        jax.block_until_ready(cat)   # attribution boundary: without
        s_dedisp = time.time() - t0  # the force, the async dedisp
        t0 = time.time()             # wall lands in the gather below
        host = fusion.gather_shards(cat, obs=obs)   # gather to host
        s_gather = time.time() - t0
        t0 = time.time()                        # per-trial disk trip
        with tempfile.TemporaryDirectory() as td:
            for i in range(numdms):
                p = os.path.join(td, "t%d.dat" % i)
                with open(p + ".tmp", "wb") as f:
                    host[i].tofile(f)
                    f.flush()
                    os.fsync(f.fileno())
                os.rename(p + ".tmp", p)
            back = np.stack([
                np.fromfile(os.path.join(td, "t%d.dat" % i),
                            dtype=np.float32)
                for i in range(numdms)])
        s_disk = time.time() - t0
        t0 = time.time()
        dev = jnp.asarray(back[:, :n])          # re-upload, 1 device
        jax.block_until_ready(dev)
        jaxtel.note_put(obs, back[:, :n].nbytes)
        s_upload = time.time() - t0
        t0 = time.time()
        res2 = searcher.search_many(np.asarray(sp_fft(dev)))
        s_search = time.time() - t0
        total = s_dedisp + s_gather + s_disk + s_upload + s_search
        if total < staged_s:        # keep the best iteration's own
            staged_s = total        # components so shares sum to 1
            t_dedisp, t_gather, t_disk = s_dedisp, s_gather, s_disk
            t_upload, t_search = s_upload, s_search
    ncands_staged = sum(len(c) for c in res2)

    cells = searcher.cfg.numz * int(searcher.rhi - searcher.rlo) * 2
    return {
        "n_devices": ndev,
        "numdms": numdms,
        "fused_s": round(fused_s, 3),
        "staged_s": round(staged_s, 3),
        "speedup": round(staged_s / max(fused_s, 1e-9), 2),
        "fused_cells_per_sec": round(cells * numdms / fused_s, 1),
        "staged_cells_per_sec": round(cells * numdms / staged_s, 1),
        "compile_s": round(compile_s, 2),
        "ncands": {"fused": ncands_fused, "staged": ncands_staged,
                   "equal": ncands_fused == ncands_staged},
        "staged_breakdown_s": {
            "dedisp": round(t_dedisp, 3),
            "gather": round(t_gather, 3),
            "disk": round(t_disk, 3),
            "reupload": round(t_upload, 3),
            "fft+search": round(t_search, 3)},
        "shares_staged": {
            "transfer": round((t_gather + t_upload) / staged_s, 3),
            "disk": round(t_disk / staged_s, 3),
            "compute": round((t_dedisp + t_search) / staged_s, 3)},
        # fused: the ONLY host transfer is the candidate-collection
        # gather — no per-DM re-upload, no disk
        "shares_fused": {
            "transfer": round(t_fgather / max(fused_s, 1e-9), 3),
            "disk": 0.0,
            "compute": round(1.0 - t_fgather / max(fused_s, 1e-9),
                             3)},
        # the fused regime's only bulk transfer is the candidate-
        # collection gather; the per-DM host round-trip is gone
        "fused_gather_bytes": int(pairs_host.nbytes),
        "staged_roundtrip_bytes": int(host.nbytes
                                      + back[:, :n].nbytes),
        "jaxtel_put_bytes": snap1["put_bytes"] - snap0["put_bytes"],
        "jaxtel_get_bytes": snap1["get_bytes"] - snap0["get_bytes"],
    }


def main():
    import jax
    from presto_tpu.obs import Observability, ObsConfig

    extended = os.environ.get("PRESTO_TPU_BENCH_EXTENDED", "1") != "0"
    # ONE obs handle across the benches: the cost probes and dispatch
    # counts accumulate into one book, rendered below as the
    # kernel_costs block (obs/costmodel)
    obs = Observability(ObsConfig(enabled=True))
    cpu_cells, cpu_dmtrials, cpu_meta = load_cpu_baseline()
    (cells_per_sec, warm_a, steady_a, cells, ncands, upload_a,
     incl_serial_cells_per_sec, incl_a, searcher,
     accel_samples) = bench_accel()
    (incl_cells_per_sec, incl_fused_s, incl_ncands,
     incl_breakdown) = bench_accel_fused_inclusive(
        searcher, steady_a, upload_a, incl_a, warm_a, obs=obs)
    (dm_per_sec, warm_d, steady_d, nsamples,
     dedisp_samples) = bench_dedisp(obs=obs)

    extra = {}
    if extended:
        cpu = cpu_meta or {}
        c3_s, c3_warm, c3_n = bench_accel3()
        c3_cpu = cpu.get("config3_seconds")
        extra["config3"] = {
            "value": round(c3_s, 2), "unit": "s",
            "cpu": round(c3_cpu, 1) if c3_cpu else None,
            "vs_baseline": round(c3_cpu / c3_s, 2) if c3_cpu else None,
            "ncands": c3_n, "warmup_s": round(c3_warm, 1)}
        (c3a_s, c3a_warm, c3a_n,
         c3a_nd) = bench_accel3_amortized(obs=obs)
        extra["config3_amortized"] = {
            "value": round(c3a_s, 3), "unit": "s/trial",
            "numdms": c3a_nd,
            "cpu": round(c3_cpu, 1) if c3_cpu else None,
            "vs_baseline": round(c3_cpu / c3a_s, 1) if c3_cpu
            else None,
            "ncands": c3a_n, "warmup_s": round(c3a_warm, 1)}
        sp_s, sp_warm, sp_n = bench_singlepulse()
        sp_cpu = cpu.get("sp_seconds")
        extra["singlepulse"] = {
            "value": round(sp_s, 2), "unit": "s",
            "cpu": round(sp_cpu, 1) if sp_cpu else None,
            "vs_baseline": round(sp_cpu / sp_s, 2) if sp_cpu else None,
            "nevents": sp_n, "warmup_s": round(sp_warm, 1)}
        (jk_cells, jk_warm, jk_s, jk_tot,
         jk_n) = bench_jerk()
        jk_cpu = cpu.get("jerk_seconds")
        extra["jerk"] = {
            "value": round(jk_cells, 1), "unit": "cells/s",
            "cpu": round(jk_cpu, 1) if jk_cpu else None,
            "vs_baseline": round(jk_cpu / jk_s, 2) if jk_cpu
            else None,
            "cpu_note": ("cpu twin sums subharmonics from the "
                         "same-w plane (conservative lower-bound "
                         "ratio; accel_ref.timed_jerk_ref)"
                         if jk_cpu else None),
            "seconds": round(jk_s, 2), "cells": jk_tot,
            "ncands": jk_n, "warmup_s": round(jk_warm, 1)}
        pp_rate, pp_warm, pp_s, pp_serial = bench_prepdata()
        pp_cpu = cpu.get("prep_seconds")
        extra["config1_prepdata"] = {
            "value": round(pp_rate, 1), "unit": "samples/s",
            "cpu": round(pp_cpu, 3) if pp_cpu else None,
            "vs_baseline": round(pp_cpu / pp_s, 2) if pp_cpu
            else None,
            "seconds": round(pp_s, 4),
            "dispatch_bound_s": round(pp_serial, 4),
            "warmup_s": round(pp_warm, 1),
            "note": ("seconds/value are the fused-seam regime: K "
                     "block dispatches issued back-to-back, forced "
                     "once (the survey's streaming loop, "
                     "pipeline/fusion.py) — the per-call dispatch "
                     "floor that bound BENCH_r05's ~0.1 s serial "
                     "number (dispatch_bound_s) amortizes away")}

    # fused vs staged sharded regime, when this host IS a mesh (the
    # same block rides into MULTICHIP_*.json via dryrun_multichip)
    mc = bench_multichip_inclusive()
    if mc is not None:
        extra["multichip_inclusive"] = mc

    from presto_tpu import tune
    tune_attr = tuning_info()
    tune_attr["lookups"] = tune.provenance()

    # kernel observatory: per-kind unit costs x dispatch counts,
    # placed on this device's roofline (peaks measured once and
    # cached in the tune fingerprint DB — obs/roofline.py)
    from presto_tpu.obs import costmodel, perfledger, roofline
    kc = costmodel.snapshot(obs)
    if kc:
        try:
            peaks = roofline.device_peaks(obs=obs)
        except Exception:
            peaks = None
        incl_breakdown["kernel_costs"] = {
            "kinds": kc.get("kinds", {}),
            "unavailable": kc.get("unavailable", {}),
            "peaks": peaks,
            "roofline": roofline.roofline_rows(kc, peaks),
        }

    # perf ledger: append this run as a median-of-k episode with MAD
    # noise bands (PRESTO_TPU_PERF_LEDGER=<path> overrides the
    # committed PERF_LEDGER.json; =0 disables).  tools/perf_gate.py
    # judges the trajectory.
    ledger_note = ""
    if os.environ.get(perfledger.ENV_LEDGER, "") != "0":
        try:
            ep = perfledger.make_episode({
                "ffdot_cells_per_sec": perfledger.metric_from_samples(
                    [cells / t for t in accel_samples], "cells/s",
                    "higher"),
                "dm_trials_per_sec": perfledger.metric_from_samples(
                    [WORKLOAD["dedisp_numdms"] / t
                     for t in dedisp_samples], "trials/s", "higher"),
                "inclusive_trial_s": perfledger.metric_from_samples(
                    [incl_fused_s], "s", "lower"),
            }, workload="full", source="bench.py",
                meta={"device": jax.devices()[0].platform})
            path = perfledger.default_ledger_path()
            led = perfledger.PerfLedger.load(path)
            led.append(ep)
            led.save(path)
            ledger_note = " | perf ledger: %s episode %s (%d total)" \
                % (path, ep["run_id"], len(led.episodes))
        except Exception as e:
            ledger_note = " | perf ledger write failed: %s" % e

    print(json.dumps({
        "metric": "ffdot_cells_per_sec_zmax200_nh8",
        "value": round(cells_per_sec, 1),
        "unit": "cells/s",
        "vs_baseline": round(cells_per_sec / cpu_cells, 2),
        # measurement-boundary marker: value/vs_baseline are DEVICE-
        # RESIDENT from round 3 on (rounds 1-2 were upload-inclusive;
        # that regime is the inclusive_* keys)
        "regime": "device-resident",
        # inclusive = the FUSED-pipeline regime from r07 on (8-bit
        # raw ingest -> device decode+FFT -> search, H2D overlapped
        # 2-deep; docs/PERFORMANCE.md): the bytes and syncs the fused
        # survey actually pays end to end.  The pre-fusion serial
        # staged number stays alongside for trajectory continuity.
        "inclusive_cells_per_sec": round(incl_cells_per_sec, 1),
        "inclusive_vs_baseline": round(incl_cells_per_sec / cpu_cells,
                                       2),
        "inclusive_regime": "fused-ingest-8bit-pipelined",
        "inclusive_trial_s": round(incl_fused_s, 4),
        "inclusive_ncands": incl_ncands,
        "inclusive_serial_cells_per_sec": round(
            incl_serial_cells_per_sec, 1),
        "inclusive_breakdown": incl_breakdown,
        "upload_s": round(upload_a, 2),
        "warmup_s": round(warm_a, 1),
        "dm_trials_per_sec": round(dm_per_sec, 1),
        "dm_trials_vs_baseline": round(dm_per_sec / cpu_dmtrials, 2),
        "cpu_baseline_measured": cpu_meta is not None,
        # config attribution: fingerprint + tuned configs (the
        # lookups dict is filled only when PRESTO_TPU_TUNE=1 was live
        # during the benches above)
        "tuning": tune_attr,
        **extra,
    }))
    print("# device=%s accel: warmup=%.1fs steady=%.2fs "
          "inclusive=%.2fs (16MB H2D ref transfer %.2fs) cells=%.3g "
          "cands=%d | dedisp: warmup=%.1fs steady=%.2fs (%d DMs x %d)"
          " | cpu baseline: %.3g cells/s, %.1f DM-trials/s (%s)"
          % (jax.devices()[0].platform, warm_a, steady_a, incl_a,
             upload_a, cells, ncands, warm_d, steady_d,
             WORKLOAD["dedisp_numdms"], WORKLOAD["dedisp_nsamples"],
             cpu_cells, cpu_dmtrials,
             "measured" if cpu_meta else "fallback")
          + ledger_note,
          file=sys.stderr)


if __name__ == "__main__":
    main()
