"""Benchmark: accelsearch F-Fdot plane throughput on the current device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: F-Fdot cells/sec for a zmax=200, numharm=8 in-core search over
a 2^21-bin spectrum (BASELINE.md config 4 analog).  A "cell" is one
fundamental-plane (z, r) power: numz * numr_halfbins, divided by the
full search wall time (plane build + harmonic sums + thresholding +
host candidate collection), steady-state (after one warmup to exclude
XLA compile).

vs_baseline: ratio against the CPU reference proxy measured on this
machine's host CPU — the same spread/FFT/cmul/IFFT/power loop in numpy
(pocketfft), 5.37e7 cells/sec — standing in for the unbuildable
FFTW/OpenMP reference build (BASELINE.md: reference publishes no
numbers; the CPU build must be timed to create them).
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

CPU_PROXY_CELLS_PER_SEC = 5.37e7  # numpy pocketfft, this host, 2026-07


def main():
    import jax
    from presto_tpu.search.accel import AccelConfig, AccelSearch

    numbins = 1 << 21
    T = 1000.0
    rng = np.random.default_rng(42)
    # noise spectrum + a few injected tones to exercise candidate paths
    re = rng.normal(size=numbins).astype(np.float32)
    im = rng.normal(size=numbins).astype(np.float32)
    pairs = np.stack([re, im], -1)
    for r0 in (12345, 123456, 765432):
        pairs[r0] = (300.0, 0.0)

    cfg = AccelConfig(zmax=200, numharm=8, sigma=6.0)
    s = AccelSearch(cfg, T=T, numbins=numbins)

    t0 = time.time()
    cands = s.search(pairs)          # warmup (includes XLA compile)
    warm = time.time() - t0

    # best of 3: the tunneled chip shows 20-30% run-to-run variance
    elapsed = float("inf")
    for _ in range(3):
        t0 = time.time()
        cands = s.search(pairs)
        elapsed = min(elapsed, time.time() - t0)

    numr = int(s.rhi - s.rlo) * 2
    cells = cfg.numz * numr
    value = cells / elapsed
    print(json.dumps({
        "metric": "ffdot_cells_per_sec_zmax200_nh8",
        "value": round(value, 1),
        "unit": "cells/s",
        "vs_baseline": round(value / CPU_PROXY_CELLS_PER_SEC, 2),
    }))
    print("# device=%s warmup=%.1fs steady=%.1fs cells=%.3g cands=%d"
          % (jax.devices()[0].platform, warm, elapsed, cells, len(cands)),
          file=sys.stderr)


if __name__ == "__main__":
    main()
